"""Serving benchmark driver: continuous vs static batching throughput,
(--paged) the paged-vs-slot KV cache comparison, (--spec) the
speculative-decoding win, (--decode-kernel) the Pallas flash-decode
kernel vs the dense attention paths, and (--chaos) the seeded
fault-injection resilience proof.

Prints ONE JSON line in the bench.py protocol ({"metric", "value",
"unit", "vs_baseline"} — extra serve-specific keys ride along).

Default mode: `value` is continuous-batching decode throughput in
tokens/s and `vs_baseline` is the ratio over STATIC batching of the
identical mixed-length request stream on the identical engine — the
Orca win this subsystem exists for, so the baseline is the pre-Orca
scheduler, not a training number. p50/p95 are per-request submit→finish
latencies under continuous batching.

--paged mode (writes BENCH_PAGED.json): at the SAME cache byte budget
(max_seqs * max_len rows), how many concurrent short requests
(prompt + generation ≪ max_len) the paged layout admits vs the slot
layout — the PagedAttention capacity win — plus CPU decode throughput
parity of the paged path against the slot path at EQUAL batch (the
gather must not tax the dense path).

--spec mode (writes BENCH_SPEC.json): decode tokens/s of the
speculative n-gram-draft engine (serving/spec.py; weight-free prompt
lookup, so no second model and CPU CI stays fast) over the plain
engine on an acceptance-friendly stream — long greedy continuations of
tiny LMs enter cycles, which prompt lookup drafts at near-1 acceptance,
so several tokens ride each verify step's single weight read. Greedy
outputs are token-identical between the two engines; only the wall
clock differs. Acceptance floor: 1.3x.

--spec-tree mode (writes BENCH_SPEC_TREE.json): token-TREE speculation
(spec_branch > 1; one verify scores a deduped draft tree and accepts
the longest surviving root-to-leaf branch) vs the linear chain at an
EQUAL verify token budget — the tree's depth x branch node count
equals the chain's k, so both arms pay for the same number of scored
rows per verify step. On the bench stream the n-gram draft's per-level
acceptance is mediocre (the cycle's trailing n-gram has competing
continuations), which is exactly the regime branching exists for: a
rejected first candidate no longer kills the whole draft. Gates —
EXIT NONZERO on miss: accepted draft tokens per verify step >= 1.2x
the equal-budget linear arm, and every greedy stream token-identical
to plain decode in BOTH arms.

--decode-kernel {auto,pallas,dense} mode (writes
BENCH_DECODE_KERNEL.json): the flash-decode kernel engine vs the dense
engine on both kv layouts over the standard mixed stream — off-TPU the
kernel runs in Pallas interpret mode and the artifact records
CORRECTNESS (greedy streams identical, step counts equal); TPU runs
fill in the real throughput ratio.

--serve-async mode (writes BENCH_ASYNC.json): the double-buffered
async engine vs the synchronous reference loop — token-identical
greedy streams required; mean tokens/s over interleaved reps plus
overlap_fraction (host work hidden behind device execution). Combined
with --chaos, the chaos gate below runs the ASYNC loop instead
(writes BENCH_CHAOS_ASYNC.json) — same zero-lost-requests and
invariant assertions, now probed inside the in-flight window.

--chunked mode (writes BENCH_CHUNKED.json): chunked prefill +
token-budget scheduling vs the unchunked scheduler on the SAME engine
over a head-of-line stream (short decoders with a long prompt landing
every third request). Gates — EXIT NONZERO on miss: p95 TTFT of short
requests admitted alongside a long prompt >= 1.3x better, p95
inter-token latency of in-flight decoders >= 1.3x better, decode
throughput >= 0.95x unchunked, and greedy streams token-identical
(chunked prefill replays the same staircase-masked computation, so
logits — and therefore tokens — must not move).

--chaos mode (writes BENCH_CHAOS.json): a seeded FaultInjector
(serving/faults.py) runs the mixed stream under OPTIMISTIC admission on
an undersized page pool while injecting NaN logits, kernel faults,
draft-proposer faults, mid-flight cancellations, latency spikes, and
page-pool steals. The driver asserts — and EXITS NONZERO on violation —
that every submitted request reaches a terminal status (no request is
ever silently lost), that the page allocator invariants hold after
every iteration, and that EVERY injected fault surfaces in the exported
telemetry metrics keyed by site (`serve_fault_injections_total`); the
artifact records goodput, preemption, and per-status counts. This is
the CI resilience gate, not a throughput number.

--recovery mode (writes BENCH_RECOVERY.json): the durable-serving gate
— crash a journaled run at the worst phase (tokens emitted, commit
flush pending), restart a fresh engine from the write-ahead journal
(serving/journal.py), and record MTTR to the first post-restart
committed token plus replayed-token counts. EXITS NONZERO unless the
crash fired mid-run, zero requests were lost, and every final stream
is token-identical to the fault-free baseline (the zero-duplicates /
zero-gaps proof).

--telemetry mode (writes BENCH_TELEMETRY.json): the observability gate
(flexflow_tpu.telemetry) — interleaved async runs with telemetry off /
in-memory / full-export prove <=2% instrumented overhead and
token-identical streams, validate the exported trace + metrics + JSONL
against the checked-in schemas, require the trace to SHOW dispatch N+1
overlapping step N's in-flight window, and hold the rolling-window p95
TTFT to exact agreement with post-hoc latency_percentiles.

--pod mode (writes BENCH_POD.json): pod-scale capacity — peak
concurrent requests on a 4-way host-partitioned page pool
(--serve-hosts, serving/distributed.py) vs the single-host engine at
an EQUAL PER-HOST page budget. Hosts are simulated (one process,
per-host admission views); capacity must scale >= 3x at 4 hosts —
EXIT NONZERO on miss.

--frontdoor mode (writes BENCH_FRONTDOOR.json): the disaggregated
serving front door (serving/frontend) under seeded OPEN-LOOP Poisson
traffic with heavy-tailed prompt lengths — the long-prompt-burst
regime where a monolithic engine's chunked prefills sit in the same
iteration loop as every other stream's decodes. Three legs on one
model: monolithic chunked engine, prefill→decode DisaggregatedPipeline
(greedy streams must be token-identical — the handoff restores the
COMMITTED pages bit-exactly, so logits cannot move), and a 2-replica
ReplicaRouter chaos leg that kills a replica mid-stream (zero lost
requests, re-route visible in replica-labelled metrics). Decode
inter-token gaps are attributed to a DECODE-TIER-ONLY clock (in
production the tiers run on separate hardware concurrently; in-process
they interleave, so wall-clock gaps would charge the decode tier for
prefill work it no longer does). Gates — EXIT NONZERO on miss:
disaggregated p99 decode ITL >= 1.3x better than monolithic, goodput
>= 0.95x monolithic, zero lost requests in the chaos leg.

--tenancy mode (writes BENCH_TENANCY.json): multi-tenant serving —
mixed-priority (gold:4 / bronze:1), mixed-LoRA-adapter open-loop
Poisson traffic at >= 2x overload, weighted-fair deficit round-robin
vs the unweighted FIFO planner on the identical arrival schedule.
Gates — EXIT NONZERO on miss: gold p95 SLO attainment under
weighted-fair >= FIFO's, bronze starvation bounded (all finish, p95
TTFT within 10x FIFO), zero lost requests, and every stream
token-identical to an uncontended isolated reference (including the
per-slot adapter deltas).

The default workload is the flagship Transformer geometry (12 layers,
hidden 1024, 16 heads — transformer.cc:79-85) recast as a decoder LM;
`--smoke` shrinks it for CPU CI.
"""

from __future__ import annotations

import json
import os
import sys


# -- shared preset geometry ---------------------------------------------------
#
# Every section (default / --paged / --spec / --decode-kernel) derives
# its request streams from ONE place, so a new benchmark mode cannot
# drift from the geometry the others measure. The streams are functions
# of (vocab, max_len) only — the same preset dict parameterizes all.


def _gen_lengths(max_len):
    """(short, long) generation lengths the streams interleave."""
    return max(2, max_len // 16), max(8, max_len // 2 - 8)


def _mixed_requests(vocab, max_len, n):
    """Short and long continuations interleaved — the regime where
    request-level batching strands slots (default + parity sections)."""
    from flexflow_tpu.serving import Request

    short, long_ = _gen_lengths(max_len)
    return [
        Request(
            rid=i,
            prompt=[(i * 7 + j) % vocab for j in range(1 + i % 6)],
            max_new_tokens=short if i % 2 == 0 else long_,
        )
        for i in range(n)
    ]


def _short_requests(vocab, max_len, n):
    """Short-everything stream (prompt 1-4 tokens, short generation) —
    the paged-capacity probe: prompt + generation ≪ max_len."""
    from flexflow_tpu.serving import Request

    gen = _gen_lengths(max_len)[0]
    return [
        Request(
            rid=i,
            prompt=[(i * 5 + j) % vocab for j in range(1 + i % 4)],
            max_new_tokens=gen,
        )
        for i in range(n)
    ]


def _long_requests(vocab, max_len, n):
    """Short prompts with near-max_len continuations — the
    acceptance-friendly speculative regime (greedy tiny LMs enter
    cycles that prompt lookup drafts at near-1 acceptance)."""
    from flexflow_tpu.serving import Request

    gen = max_len - 16
    return [
        Request(
            rid=i,
            prompt=[(i * 5 + j) % vocab for j in range(1 + i % 4)],
            max_new_tokens=gen,
        )
        for i in range(n)
    ]


def _poisson_arrivals(n, rate, rng):
    """Seeded open-loop arrival schedule: n arrival offsets (seconds
    from t0) with exponential inter-arrival gaps at `rate` requests/s.
    EVERY open-loop mode draws its schedule here so two legs replay the
    identical offered load — an inline redraw per leg would hand each
    leg a different burst pattern and the comparison would measure
    traffic luck, not the serving policy."""
    import numpy as np

    gaps = rng.exponential(1.0 / float(rate), size=int(n))
    return [float(t) for t in np.cumsum(gaps)]


def _heavy_tailed_prompts(vocab, max_len, n, rng):
    """Heavy-tailed prompt lengths (Pareto tail clipped to the context
    window): mostly short conversational prompts with occasional
    near-max_len documents — the long-prompt-burst regime the
    disaggregated front door exists for."""
    lens = [
        int(min(max_len * 3 // 4, 2 + rng.pareto(1.1) * 6))
        for _ in range(n)
    ]
    # at least one guaranteed document per batch: the tail must fire
    # even on tiny --smoke batches
    lens[n // 2] = max_len * 3 // 4
    return [
        [int(rng.integers(1, vocab)) for _ in range(ln)] for ln in lens
    ]


def run(
    layers: int,
    hidden: int,
    heads: int,
    vocab: int,
    max_seqs: int,
    max_len: int,
    num_requests: int,
    reps: int = 2,
):
    from flexflow_tpu.serving import (
        ContinuousBatchingScheduler,
        ServeConfig,
        StaticBatchingScheduler,
        build_scheduler,
        latency_percentiles,
    )

    model = _build_lm(layers, hidden, heads, vocab, max_seqs, max_len)

    def requests():
        return _mixed_requests(vocab, max_len, num_requests)

    serve = ServeConfig(max_seqs=max_seqs, max_seq_len=max_len)
    _, engine, _ = build_scheduler(model, serve)
    for cls in (ContinuousBatchingScheduler, StaticBatchingScheduler):
        cls(engine).run(requests()[: max_seqs + 1])  # warm jit signatures

    best = {}
    latencies = ttft = None
    for name, cls in (
        ("static", StaticBatchingScheduler),
        ("continuous", ContinuousBatchingScheduler),
    ):
        runs = []
        for _ in range(reps):
            sched = cls(engine)
            done = sched.run(requests())
            runs.append(sched.stats)
            if name == "continuous":
                latencies = latency_percentiles(done, (50, 95))
                ttft = latency_percentiles(done, (50,), metric="ttft")
        best[name] = max(s.tokens_per_s for s in runs)

    return {
        "metric": (
            f"serve_decoder_{layers}L_{hidden}h_continuous_throughput"
        ),
        "value": round(best["continuous"], 2),
        "unit": "tokens/s",
        # ratio over static batching of the same stream (>1 = Orca win)
        "vs_baseline": round(best["continuous"] / best["static"], 3),
        "static_tokens_per_s": round(best["static"], 2),
        "p50_latency_ms": round(latencies[50] * 1e3, 2),
        "p95_latency_ms": round(latencies[95] * 1e3, 2),
        "p50_ttft_ms": round(ttft[50] * 1e3, 2),
    }


def _build_lm(layers, hidden, heads, vocab, max_seqs, max_len):
    import jax

    from flexflow_tpu import (
        DataType,
        FFConfig,
        FFModel,
        LossType,
        SGDOptimizer,
    )
    from flexflow_tpu.models import build_decoder_lm

    cfg = FFConfig(batch_size=max_seqs)
    model = FFModel(cfg)
    tok = model.create_tensor(
        [max_seqs, max_len], dtype=DataType.INT32, name="tokens"
    )
    build_decoder_lm(
        model,
        tok,
        vocab_size=vocab,
        hidden=hidden,
        num_heads=heads,
        num_layers=layers,
        ff_dim=4 * hidden,
    )
    model.compile(
        optimizer=SGDOptimizer(lr=0.01),
        loss_type=LossType.SPARSE_CATEGORICAL_CROSSENTROPY,
        metrics=[],
        devices=jax.devices()[:1],
    )
    return model


def run_paged(
    layers: int,
    hidden: int,
    heads: int,
    vocab: int,
    max_seqs: int,
    max_len: int,
    num_requests: int,
    reps: int = 2,
):
    """Paged-vs-slot comparison at a FIXED cache byte budget
    (max_seqs * max_len rows per layer).

    Capacity: a stream of short requests (prompt + generation ≪
    max_len) saturates both layouts; `peak_in_flight` is how many the
    admission gate let run concurrently. The slot layout caps at
    max_seqs (each slot pins max_len rows); the paged layout packs
    ceil(need / page_size) pages per request from the same pool.

    Throughput parity: the default-geometry paged engine (identical
    capacity AND identical admission schedule to slot) against the slot
    engine on the standard mixed stream at EQUAL batch — the block-table
    gather must cost < 10% on CPU decode throughput."""
    from flexflow_tpu.serving import (
        ContinuousBatchingScheduler,
        ServeConfig,
        build_scheduler,
        default_page_size,
    )

    model = _build_lm(layers, hidden, heads, vocab, max_seqs, max_len)
    page_size = default_page_size(max_len)
    budget_pages = max_seqs * max_len // page_size

    # short-request profile (prompt 1-4 tokens, generation max_len // 16)
    gen = _gen_lengths(max_len)[0]
    need_pages = -(-(4 + gen) // page_size)
    paged_seqs = max(max_seqs, budget_pages // need_pages)

    def short_requests(n):
        return _short_requests(vocab, max_len, n)

    def mixed_requests():
        return _mixed_requests(vocab, max_len, num_requests)

    # -- capacity at a fixed byte budget ------------------------------------
    peak = {}
    n_short = 2 * paged_seqs
    for name, serve in (
        ("slot", ServeConfig(max_seqs=max_seqs, max_seq_len=max_len,
                             kv_layout="slot")),
        ("paged", ServeConfig(max_seqs=paged_seqs, max_seq_len=max_len,
                              kv_layout="paged", kv_page_size=page_size,
                              kv_pages=budget_pages)),
    ):
        sched, _, _ = build_scheduler(model, serve)
        sched.run(short_requests(n_short))
        peak[name] = sched.stats.peak_in_flight
    capacity_ratio = peak["paged"] / peak["slot"]

    # -- decode throughput parity at equal batch ----------------------------
    tps = {}
    for name in ("slot", "paged"):
        serve = ServeConfig(
            max_seqs=max_seqs, max_seq_len=max_len, kv_layout=name
        )
        _, engine, _ = build_scheduler(model, serve)
        ContinuousBatchingScheduler(engine).run(
            mixed_requests()[: max_seqs + 1]
        )  # warm jit signatures
        best = 0.0
        for _ in range(reps):
            sched = ContinuousBatchingScheduler(engine)
            sched.run(mixed_requests())
            best = max(best, sched.stats.tokens_per_s)
        tps[name] = best

    return {
        "metric": f"serve_paged_capacity_{layers}L_{hidden}h",
        "value": round(capacity_ratio, 3),
        "unit": "x_concurrent_short_requests_vs_slot",
        # capacity over the slot layout at the same byte budget
        # (acceptance floor: 1.5x)
        "vs_baseline": round(capacity_ratio, 3),
        "page_size": page_size,
        "num_pages": budget_pages,
        "paged_peak_in_flight": peak["paged"],
        "slot_peak_in_flight": peak["slot"],
        "paged_tokens_per_s": round(tps["paged"], 2),
        "slot_tokens_per_s": round(tps["slot"], 2),
        # paged/slot CPU decode throughput at equal batch (parity
        # target: >= 0.9)
        "throughput_ratio": round(tps["paged"] / tps["slot"], 3),
    }


def run_prefix(
    layers: int,
    hidden: int,
    heads: int,
    vocab: int,
    max_seqs: int,
    max_len: int,
    num_requests: int,
    reps: int = 2,
):
    """Multi-tenant capacity at a FIXED cache byte budget: hashed
    prefix sharing first, int8 token pools on top.

    Capacity: every request carries the same long prompt prefix (the
    system-prompt regime) plus a short unique tail. Admission is
    optimistic, so a request whose prefix pages are already published
    is charged only its fresh pages; `peak_in_flight` is how many the
    page pool let run concurrently. Three engines at the SAME HBM byte
    budget (slots sized to the pool so only pages bind anywhere):

      fp32          — paged, no sharing (baseline)
      fp32 + prefix — full prefix pages refcounted across tenants
      int8 + prefix — 1-byte rows buy ~4x the pages at equal bytes,
                      minus the fp32 dequant-scale side pools

    Throughput parity: int8 + prefix against the plain paged fp32
    engine on the decode-dominated stream (short prompts, near-max_len
    generations) at EQUAL batch — the gate is DECODE throughput, so the
    stream must be decode-bound: dequant fused into the decode gather
    must stay within 5% on CPU. (Prefill pays a one-time quantize
    round trip per prompt, but in the shared-prefix regime the prefix
    pages skip prefill entirely — that cost is the capacity section's
    subject, not this gate's.)"""
    from flexflow_tpu.serving import (
        ContinuousBatchingScheduler,
        Request,
        ServeConfig,
        build_scheduler,
        default_page_size,
    )

    model = _build_lm(layers, hidden, heads, vocab, max_seqs, max_len)
    page_size = default_page_size(max_len)
    head_dim = hidden // heads
    budget_pages = max_seqs * max_len // page_size

    # equal-HBM int8 pool: 1-byte rows shrink a page 4x; the fp32
    # dequant scales (one per page per head, K and V) claw a sliver back
    fp32_page_bytes = 2 * 4 * page_size * heads * head_dim
    int8_page_bytes = 2 * 1 * page_size * heads * head_dim + 2 * 4 * heads
    int8_pages = budget_pages * fp32_page_bytes // int8_page_bytes

    # shared-prefix profile: a common prompt of whole pages (half the
    # context) + a 1-3 token unique tail + a short generation
    pref_pages = max(1, (max_len // 2) // page_size)
    pref = [(j * 11 + 3) % vocab for j in range(pref_pages * page_size)]
    gen = _gen_lengths(max_len)[0]

    def shared_requests(n):
        return [
            Request(
                rid=i,
                prompt=pref
                + [(i * 13 + j + 1) % vocab for j in range(1 + i % 3)],
                # the first request anchors the prefix live while the
                # rest churn, so later admissions land on pages the
                # earlier batch already published
                max_new_tokens=2 * gen if i == 0 else max(2, gen - i % 3),
            )
            for i in range(n)
        ]

    peak, hits = {}, {}
    for name, pages, dtype, prefix in (
        ("fp32", budget_pages, "fp32", False),
        ("fp32_prefix", budget_pages, "fp32", True),
        ("int8_prefix", int8_pages, "int8", True),
    ):
        # a live request always holds >= 1 page, so `pages` slots make
        # the pool — never the slot count — the binding constraint
        slots = max(max_seqs, pages)
        serve = ServeConfig(
            max_seqs=slots, max_seq_len=max_len, kv_layout="paged",
            kv_page_size=page_size, kv_pages=pages, kv_dtype=dtype,
            prefix_cache=prefix, admission="optimistic",
        )
        sched, _, _ = build_scheduler(model, serve)
        sched.run(shared_requests(2 * slots))
        peak[name] = sched.stats.peak_in_flight
        hits[name] = sched.stats.prefix_hits
    prefix_ratio = peak["fp32_prefix"] / peak["fp32"]
    int8_ratio = peak["int8_prefix"] / peak["fp32_prefix"]

    # -- decode throughput parity at equal batch ----------------------------
    def decode_requests():
        return _long_requests(vocab, max_len, num_requests)

    tps = {}
    for name, dtype, prefix in (
        ("fp32", "fp32", False),
        ("int8_prefix", "int8", True),
    ):
        serve = ServeConfig(
            max_seqs=max_seqs, max_seq_len=max_len, kv_layout="paged",
            kv_dtype=dtype, prefix_cache=prefix,
        )
        _, engine, _ = build_scheduler(model, serve)
        ContinuousBatchingScheduler(engine).run(
            decode_requests()[: max_seqs + 1]
        )  # warm jit signatures
        best = 0.0
        for _ in range(reps):
            sched = ContinuousBatchingScheduler(engine)
            sched.run(decode_requests())
            best = max(best, sched.stats.tokens_per_s)
        tps[name] = best

    return {
        "metric": f"serve_prefix_capacity_{layers}L_{hidden}h",
        "value": round(prefix_ratio, 3),
        "unit": "x_concurrent_shared_prefix_requests",
        # concurrency over plain paged fp32 at the same byte budget
        # (acceptance floor: 2x)
        "vs_baseline": round(prefix_ratio, 3),
        "page_size": page_size,
        "prefix_tokens": pref_pages * page_size,
        "fp32_pages": budget_pages,
        "int8_pages": int8_pages,
        "fp32_peak_in_flight": peak["fp32"],
        "prefix_peak_in_flight": peak["fp32_prefix"],
        "int8_peak_in_flight": peak["int8_prefix"],
        "prefix_hits": hits["fp32_prefix"],
        "int8_prefix_hits": hits["int8_prefix"],
        # additional capacity from int8 pools at equal bytes
        # (acceptance floor: 1.8x)
        "int8_capacity_ratio": round(int8_ratio, 3),
        "fp32_tokens_per_s": round(tps["fp32"], 2),
        "int8_tokens_per_s": round(tps["int8_prefix"], 2),
        # int8+prefix / fp32 CPU decode throughput at equal batch
        # (parity floor: 0.95)
        "throughput_ratio": round(tps["int8_prefix"] / tps["fp32"], 3),
    }


def run_pod(
    layers: int,
    hidden: int,
    heads: int,
    vocab: int,
    max_seqs: int,
    max_len: int,
    num_requests: int,
    hosts: int = 4,
):
    """Pod capacity scaling (writes BENCH_POD.json): peak concurrent
    requests on a `hosts`-way host-partitioned page pool vs the
    single-host engine at an EQUAL PER-HOST page budget.

    The single-host baseline runs today's engine (no placement) over B
    pages; the pod run sets --serve-hosts so build_scheduler applies a
    serving placement and partitions hosts*B pages into per-host free
    views (serving/distributed.py). Requests hold a fixed worst case of
    two pages each (one-page prompt + a tail that may cross the page
    boundary), so admission capacity is pages-bound on every host and
    peak_in_flight should scale ~linearly with the simulated host count
    (acceptance floor: 3x at hosts=4). Hosts are SIMULATED: one process,
    per-host admission views — the CPU CI posture; a real pod replaces
    the simulation with jax.process_count() partitions."""
    from flexflow_tpu.serving import (
        Request,
        ServeConfig,
        build_scheduler,
        default_page_size,
    )

    page_size = default_page_size(max_len)
    # per-host budget: the pages the single-host smoke geometry carries
    pages_per_host = max_seqs * max_len // page_size

    def requests(n):
        # one-page prompts + 2 generated tokens: worst case 2 pages per
        # request under the reserve admission policy, on every host
        return [
            Request(
                rid=i,
                prompt=[(i * 7 + j + 1) % vocab for j in range(page_size)],
                max_new_tokens=2,
            )
            for i in range(n)
        ]

    peak, tps = {}, {}
    for nh in (1, hosts):
        model = _build_lm(layers, hidden, heads, vocab, max_seqs, max_len)
        pages = pages_per_host * nh
        slots = pages  # slots never bind; the page pool is the constraint
        serve = ServeConfig(
            max_seqs=slots, max_seq_len=max_len, kv_layout="paged",
            kv_page_size=page_size, kv_pages=pages,
            serve_hosts=nh if nh > 1 else 0,
        )
        sched, _, cache = build_scheduler(model, serve)
        assert cache.num_hosts == nh
        sched.run(requests(2 * slots))
        peak[nh] = sched.stats.peak_in_flight
        tps[nh] = sched.stats.tokens_per_s

    ratio = peak[hosts] / max(1, peak[1])
    return {
        "metric": f"serve_pod_capacity_{layers}L_{hidden}h_{hosts}hosts",
        "value": round(ratio, 3),
        "unit": "x_peak_concurrent_requests",
        # concurrency over the single-host engine at equal per-host
        # pages (acceptance floor: 3x at hosts=4)
        "vs_baseline": round(ratio, 3),
        "hosts": hosts,
        "page_size": page_size,
        "pages_per_host": pages_per_host,
        "single_host_peak_in_flight": peak[1],
        "pod_peak_in_flight": peak[hosts],
        "single_host_tokens_per_s": round(tps[1], 2),
        "pod_tokens_per_s": round(tps[hosts], 2),
    }


def run_spec(
    layers: int,
    hidden: int,
    heads: int,
    vocab: int,
    max_seqs: int,
    max_len: int,
    num_requests: int,
    reps: int = 2,
    spec_k: int = 4,
):
    """Speculative (n-gram draft) vs plain decode at identical greedy
    output. The stream is acceptance-friendly by construction: short
    prompts with long continuations — a greedy tiny LM settles into a
    cycle within a few tokens, and prompt lookup then proposes the
    cycle's continuation at near-1 acceptance, so each verify step's
    single weight pass carries several tokens. Novel-text acceptance
    would be lower; optimize_spec_k prices that trade from the measured
    rate this bench records."""
    from flexflow_tpu.serving import (
        ContinuousBatchingScheduler,
        ServeConfig,
        build_scheduler,
        latency_percentiles,
    )

    model = _build_lm(layers, hidden, heads, vocab, max_seqs, max_len)

    def requests():
        return _long_requests(vocab, max_len, num_requests)

    results = {}
    stats = {}
    decode_lat = {}
    streams = {}
    for name, serve in (
        ("plain", ServeConfig(max_seqs=max_seqs, max_seq_len=max_len)),
        ("spec", ServeConfig(max_seqs=max_seqs, max_seq_len=max_len,
                             spec_draft="ngram", spec_k=spec_k)),
    ):
        # ONE engine per mode (fresh schedulers share its jitted steps,
        # like run()); the warm run compiles every signature off the clock
        warm, engine, _ = build_scheduler(model, serve)
        warm.run(requests()[: max_seqs + 1])
        best = 0.0
        for _ in range(reps):
            sched = ContinuousBatchingScheduler(
                engine, proposer=warm.proposer, spec_k=serve.spec_k
            )
            done = sched.run(requests())
            if sched.stats.tokens_per_s > best:
                best = sched.stats.tokens_per_s
                stats[name] = sched.stats
                decode_lat[name] = latency_percentiles(
                    done, (50,), metric="decode_per_token"
                )
                streams[name] = {
                    r.rid: tuple(r.generated) for r in done
                }
        results[name] = best
    # greedy spec decode is token-identical up to argmax near-ties:
    # verify and decode are different XLA programs (w-query vs 1-query
    # reductions), so logits can differ in the last ulp and flip a tied
    # argmax — same caveat as any cross-program identity. The controlled
    # test configs assert exact identity (tests/test_spec_decode.py);
    # the bench records how many streams matched so a REAL divergence
    # (not a tie) is visible in the artifact.
    matched = sum(
        1 for rid in streams["plain"]
        if streams["spec"].get(rid) == streams["plain"][rid]
    )

    ratio = results["spec"] / results["plain"]
    s = stats["spec"]
    return {
        "metric": f"serve_spec_decode_{layers}L_{hidden}h",
        "value": round(results["spec"], 2),
        "unit": "tokens/s",
        # speculative over plain decode throughput, identical greedy
        # streams (acceptance floor: 1.3x)
        "vs_baseline": round(ratio, 3),
        "plain_tokens_per_s": round(results["plain"], 2),
        "spec_k": spec_k,
        "draft": "ngram",
        "acceptance_rate": round(s.acceptance_rate, 3),
        # tokens each verify step emitted (prefill's first tokens excluded)
        "tokens_per_verify": round(
            (s.tokens_generated - s.finished_requests) / s.verify_steps, 2
        ) if s.verify_steps else 0.0,
        "verify_steps": s.verify_steps,
        "greedy_streams_match": f"{matched}/{len(streams['plain'])}",
        "plain_p50_decode_ms_per_token": round(
            decode_lat["plain"][50] * 1e3, 3
        ),
        "spec_p50_decode_ms_per_token": round(
            decode_lat["spec"][50] * 1e3, 3
        ),
    }


def run_spec_tree(
    layers: int,
    hidden: int,
    heads: int,
    vocab: int,
    max_seqs: int,
    max_len: int,
    num_requests: int,
    reps: int = 2,
    spec_k: int = 4,
    spec_branch: int = 3,
):
    """Token-tree speculation (depth spec_k x branch spec_branch) vs the
    linear chain at EQUAL verify token budget: the linear arm drafts
    k = spec_k * spec_branch tokens per verify, the tree arm the same
    number of NODES — both pay for 1 + k scored rows per slot per step.
    At the stream's mediocre per-level n-gram acceptance (distinct
    historical continuations of the trailing bigram compete), the chain
    wastes every row past its first rejection while the tree's sibling
    branches keep levels alive — the accepted-tokens-per-verify ratio
    this bench gates on. Greedy streams must stay token-identical to
    plain decode in all three legs."""
    from flexflow_tpu.serving import (
        ContinuousBatchingScheduler,
        ServeConfig,
        build_scheduler,
    )

    model = _build_lm(layers, hidden, heads, vocab, max_seqs, max_len)
    nodes = spec_k * spec_branch

    def requests():
        return _long_requests(vocab, max_len, num_requests)

    results = {}
    stats = {}
    streams = {}
    for name, serve in (
        ("plain", ServeConfig(max_seqs=max_seqs, max_seq_len=max_len)),
        ("linear", ServeConfig(max_seqs=max_seqs, max_seq_len=max_len,
                               spec_draft="ngram", spec_k=nodes)),
        ("tree", ServeConfig(max_seqs=max_seqs, max_seq_len=max_len,
                             spec_draft="ngram", spec_k=spec_k,
                             spec_branch=spec_branch)),
    ):
        warm, engine, _ = build_scheduler(model, serve)
        warm.run(requests()[: max_seqs + 1])
        best = 0.0
        for _ in range(reps):
            sched = ContinuousBatchingScheduler(
                engine, proposer=warm.proposer, spec_k=serve.spec_k,
                spec_branch=serve.spec_branch,
            )
            done = sched.run(requests())
            if sched.stats.tokens_per_s >= best:
                best = sched.stats.tokens_per_s
                stats[name] = sched.stats
                streams[name] = {
                    r.rid: tuple(r.generated) for r in done
                }
        results[name] = best

    def accepted_per_verify(s):
        return (
            s.draft_tokens_accepted / s.verify_steps
            if s.verify_steps else 0.0
        )

    apv = {n: accepted_per_verify(stats[n]) for n in ("linear", "tree")}
    matched = {
        n: sum(
            1 for rid in streams["plain"]
            if streams[n].get(rid) == streams["plain"][rid]
        )
        for n in ("linear", "tree")
    }
    st = stats["tree"]
    return {
        "metric": f"serve_spec_tree_{layers}L_{hidden}h",
        "value": round(apv["tree"], 3),
        "unit": "accepted tokens/verify",
        # tree over equal-budget linear accepted-per-verify (floor 1.2x)
        "vs_baseline": round(
            apv["tree"] / apv["linear"] if apv["linear"] else 0.0, 3
        ),
        "verify_token_budget": 1 + nodes,
        "tree_depth": spec_k,
        "tree_branch": spec_branch,
        "linear_k": nodes,
        "draft": "ngram",
        "linear_accepted_per_verify": round(apv["linear"], 3),
        "linear_acceptance_rate": round(
            stats["linear"].acceptance_rate, 3
        ),
        "tree_acceptance_rate": round(st.acceptance_rate, 3),
        "tree_verify_steps": st.tree_verify_steps,
        "tree_nodes_proposed": st.tree_nodes_proposed,
        "plain_tokens_per_s": round(results["plain"], 2),
        "linear_tokens_per_s": round(results["linear"], 2),
        "tree_tokens_per_s": round(results["tree"], 2),
        "greedy_streams_match": {
            n: f"{matched[n]}/{len(streams['plain'])}"
            for n in ("linear", "tree")
        },
    }


def run_decode_kernel(
    layers: int,
    hidden: int,
    heads: int,
    vocab: int,
    max_seqs: int,
    max_len: int,
    num_requests: int,
    reps: int = 2,
    decode_kernel: str = "pallas",
):
    """Pallas flash-decode kernel (ops/pallas/decode_kernel.py) vs the
    dense attention paths at identical greedy output, on BOTH kv
    layouts, over the standard mixed stream.

    Off-TPU the kernel runs in Pallas interpret mode, so this section's
    job there is the correctness artifact CI records: every greedy
    stream must match the dense engine's and the step counts must be
    equal (the kernel changes how a step computes, never how many steps
    run). The throughput ratio only means something on a real TPU —
    interpret mode is orders of magnitude off the hardware kernel."""
    import jax

    from flexflow_tpu.serving import (
        ContinuousBatchingScheduler,
        ServeConfig,
        build_scheduler,
    )

    model = _build_lm(layers, hidden, heads, vocab, max_seqs, max_len)
    per_layout = {}
    for layout in ("slot", "paged"):
        tps, steps, streams = {}, {}, {}
        for label, mode in (("dense", "dense"), ("kernel", decode_kernel)):
            serve = ServeConfig(
                max_seqs=max_seqs,
                max_seq_len=max_len,
                kv_layout=layout,
                decode_kernel=mode,
            )
            warm, engine, _ = build_scheduler(model, serve)
            warm.run(_mixed_requests(vocab, max_len, max_seqs + 1))
            best = 0.0
            for _ in range(reps):
                sched = ContinuousBatchingScheduler(engine)
                done = sched.run(
                    _mixed_requests(vocab, max_len, num_requests)
                )
                if sched.stats.tokens_per_s >= best:
                    best = sched.stats.tokens_per_s
                    steps[label] = sched.stats.decode_steps
                    streams[label] = {r.rid: tuple(r.generated) for r in done}
            tps[label] = best
        matched = sum(
            1
            for rid in streams["dense"]
            if streams["kernel"].get(rid) == streams["dense"][rid]
        )
        per_layout[layout] = {
            "kernel_tokens_per_s": round(tps["kernel"], 2),
            "dense_tokens_per_s": round(tps["dense"], 2),
            "throughput_ratio": round(tps["kernel"] / tps["dense"], 3)
            if tps["dense"]
            else 0.0,
            "streams_match": f"{matched}/{len(streams['dense'])}",
            "decode_steps_kernel": steps["kernel"],
            "decode_steps_dense": steps["dense"],
        }
    interpret = jax.default_backend() != "tpu"
    return {
        "metric": f"serve_decode_kernel_{layers}L_{hidden}h",
        "value": per_layout["paged"]["kernel_tokens_per_s"],
        "unit": "tokens/s",
        # kernel over dense decode throughput on the paged layout —
        # meaningful on TPU only; in interpret mode the artifact's
        # purpose is the streams_match / step-count correctness record
        "vs_baseline": per_layout["paged"]["throughput_ratio"],
        "decode_kernel": decode_kernel,
        "interpret_mode": interpret,
        "slot": per_layout["slot"],
        "paged": per_layout["paged"],
    }


def run_async(
    layers: int,
    hidden: int,
    heads: int,
    vocab: int,
    max_seqs: int,
    max_len: int,
    num_requests: int,
    reps: int = 4,
):
    """Async double-buffered engine (--serve-async) vs the synchronous
    reference loop on the SAME engine and the standard mixed stream.

    Token identity: greedy streams must match the sync scheduler's
    exactly (the step sequence per slot is unchanged — only the
    dispatch/reconcile timing moves). Throughput compares MEANS over
    interleaved reps, not best-of: on CPU the step is host-bound (the
    device finishes each ~100µs step long before the ~ms of host
    scheduling around it), so the async win there is tail behavior —
    the pipeline absorbs host jitter that serializes into the sync
    loop's wall clock — and best-of-N reports exactly the lucky run
    where no jitter happened. overlap_fraction is the structural
    number: fraction of each dispatch→reconcile window the host spent
    working instead of blocked (sync ≈ half its tiny window by
    construction of the measurement; async ≈ 1). The wall-clock ratio
    on real accelerators — where the device step dwarfs host work and
    overlap converts directly into throughput — awaits TPU hardware."""
    from flexflow_tpu.serving import (
        AsyncContinuousBatchingScheduler,
        ContinuousBatchingScheduler,
        ServeConfig,
        build_scheduler,
    )

    model = _build_lm(layers, hidden, heads, vocab, max_seqs, max_len)

    def requests():
        return _mixed_requests(vocab, max_len, num_requests)

    serve = ServeConfig(max_seqs=max_seqs, max_seq_len=max_len)
    _, engine, _ = build_scheduler(model, serve)
    modes = (
        ("sync", ContinuousBatchingScheduler),
        ("async", AsyncContinuousBatchingScheduler),
    )
    for _, cls in modes:  # warm every jit signature off the clock
        cls(engine).run(requests()[: max_seqs + 1])
    tps = {name: [] for name, _ in modes}
    stats = {}
    streams = {}
    for _ in range(reps):  # interleaved: both modes see the same drift
        for name, cls in modes:
            sched = cls(engine)
            done = sched.run(requests())
            tps[name].append(sched.stats.tokens_per_s)
            stats[name] = sched.stats
            streams.setdefault(
                name, {r.rid: tuple(r.generated) for r in done}
            )
    mean = {n: sum(v) / len(v) for n, v in tps.items()}
    matched = sum(
        1
        for rid in streams["sync"]
        if streams["async"].get(rid) == streams["sync"][rid]
    )
    return {
        "metric": f"serve_async_engine_{layers}L_{hidden}h",
        "value": round(mean["async"], 2),
        "unit": "tokens/s",
        # async over sync mean decode throughput, identical greedy
        # streams (CPU target: >= 1.0 — parity plus jitter absorption;
        # the overlap win in wall clock awaits TPU hardware)
        "vs_baseline": round(mean["async"] / mean["sync"], 3),
        "sync_tokens_per_s": round(mean["sync"], 2),
        "best_async_tokens_per_s": round(max(tps["async"]), 2),
        "best_sync_tokens_per_s": round(max(tps["sync"]), 2),
        "reps": reps,
        "overlap_fraction": round(stats["async"].overlap_fraction, 3),
        "sync_overlap_fraction": round(stats["sync"].overlap_fraction, 3),
        "mean_dispatch_gap_ms": round(
            stats["async"].mean_dispatch_gap_s * 1e3, 3
        ),
        "streams_match": f"{matched}/{len(streams['sync'])}",
        "tpu_ratio": "pending hardware",
    }


def run_multistep(
    layers: int,
    hidden: int,
    heads: int,
    vocab: int,
    max_seqs: int,
    max_len: int,
    num_requests: int,
    reps: int = 3,
    max_fused_steps: int = 8,
):
    """Device-resident multi-step decode (--decode-multistep) vs the
    step-at-a-time reference on the quiet-stretch regime the feature
    targets: one admission wave of max_seqs long decoders, then a
    scheduler-invariant decode stretch (no admissions, no phase
    changes) that fuses into K-step lax.scan windows.

    The gated number is steps-per-host-sync: committed tokens per host
    round-trip (every step reconcile is exactly one sync). The fused
    loop must land >= 4x the step-at-a-time loop's — the host-overhead
    amortization the fused window exists for — with every greedy
    stream token-identical. The WALL-CLOCK ratio is recorded unguarded
    on CPU: each host sync there costs ~µs against a host-bound ~ms
    step, so the sync savings is structural, not wall-clock, until a
    real accelerator (where a sync costs ~100µs of dead device time)
    carries it."""
    from flexflow_tpu.serving import Request, ServeConfig, build_scheduler

    model = _build_lm(layers, hidden, heads, vocab, max_seqs, max_len)
    long_gen = max(8, max_len // 2 - 8)

    def requests():
        return [
            Request(
                rid=i,
                prompt=[(i * 7 + j) % vocab for j in range(2 + i % 3)],
                max_new_tokens=long_gen,
            )
            for i in range(max_seqs)
        ]

    def build(multistep):
        serve = ServeConfig(
            max_seqs=max_seqs,
            max_seq_len=max_len,
            decode_multistep=multistep,
            max_fused_steps=max_fused_steps,
        )
        return build_scheduler(model, serve)

    modes = {"plain": False, "fused": True}
    schedulers = {}
    for name, multistep in modes.items():  # warm the jits off the clock
        sched, _, _ = build(multistep)
        sched.run(requests()[:2])
    tps = {name: [] for name in modes}
    stats = {}
    streams = {}
    for _ in range(reps):  # interleaved: both modes see the same drift
        for name, multistep in modes.items():
            sched, _, _ = build(multistep)
            done = sched.run(requests())
            tps[name].append(sched.stats.tokens_per_s)
            stats[name] = sched.stats
            schedulers[name] = sched
            streams.setdefault(
                name, {r.rid: tuple(r.generated) for r in done}
            )
    mean = {n: sum(v) / len(v) for n, v in tps.items()}
    steps_per_sync = {
        n: s.tokens_generated / max(1, s.host_syncs)
        for n, s in stats.items()
    }
    matched = sum(
        1
        for rid in streams["plain"]
        if streams["fused"].get(rid) == streams["plain"][rid]
    )
    fused = stats["fused"]
    return {
        "metric": f"serve_multistep_decode_{layers}L_{hidden}h",
        "value": round(steps_per_sync["fused"], 2),
        "unit": "steps/host-sync",
        # fused over step-at-a-time steps-per-host-sync, identical
        # greedy streams (gate: >= 4.0 on the medium CPU preset)
        "vs_baseline": round(
            steps_per_sync["fused"] / steps_per_sync["plain"], 3
        ),
        "plain_steps_per_sync": round(steps_per_sync["plain"], 2),
        "host_syncs_per_token": round(
            fused.host_syncs_per_token, 4
        ),
        "plain_host_syncs_per_token": round(
            stats["plain"].host_syncs_per_token, 4
        ),
        "multistep_windows": fused.multistep_windows,
        "multistep_steps": fused.multistep_steps,
        "mean_window_depth": round(
            fused.multistep_steps / max(1, fused.multistep_windows), 2
        ),
        "max_fused_steps": max_fused_steps,
        "tokens_per_s": round(mean["fused"], 2),
        "plain_tokens_per_s": round(mean["plain"], 2),
        # unguarded on CPU (host-bound steps; see docstring) — the
        # structural win is the sync count above
        "wallclock_ratio": round(mean["fused"] / mean["plain"], 3),
        "reps": reps,
        "streams_match": f"{matched}/{len(streams['plain'])}",
        "tpu_ratio": "pending hardware",
    }


def _hol_requests(vocab, max_len, n):
    """Short decoders with a long-prompt request every third rid — the
    head-of-line regime chunked prefill exists for: by the time a long
    prompt is admitted, short requests are decoding in flight, and a
    monolithic prefill stalls every one of them for a full prompt's
    worth of compute. Short generation lengths are staggered so slots
    free at different iterations and later longs land mid-decode."""
    from flexflow_tpu.serving import Request

    long_prompt = max_len // 2
    short_gen = max(6, max_len // 16)
    out = []
    for i in range(n):
        if i % 3 == 2:
            out.append(
                Request(
                    rid=i,
                    prompt=[(i * 11 + j) % vocab
                            for j in range(long_prompt)],
                    max_new_tokens=2,
                )
            )
        else:
            out.append(
                Request(
                    rid=i,
                    prompt=[(i * 7 + j) % vocab for j in range(1 + i % 3)],
                    max_new_tokens=short_gen + 2 * (i % 3),
                )
            )
    return out


def run_chunked(
    layers: int,
    hidden: int,
    heads: int,
    vocab: int,
    max_seqs: int,
    max_len: int,
    num_requests: int,
    reps: int = 3,
):
    """Chunked prefill (--chunked) vs the unchunked continuous
    scheduler on the SAME engine over the head-of-line stream.

    Two latency populations, both pooled over interleaved reps:

    * blocked shorts — short requests admitted in the same iteration a
      long prompt was; unchunked, their first token waits on the whole
      monolithic prefill, chunked it arrives after one budget-sized
      iteration. TTFT here is admission→first-token (from the request
      event log), not submit→first-token: in a closed-loop bench every
      request is submitted at t0, so submit-relative TTFT for a
      late-admitted request is all queue wait and would measure total
      elapsed time, not the head-of-line block this mode removes.
    * in-flight decoders — every inter-token gap the SLO window
      observed; a monolithic prefill inflates one gap per decoder per
      long admission, chunking spreads that cost across budget-capped
      iterations.

    Throughput is the guard rail, not the headline: chunking pays more
    dispatches for the same token work, and the gate holds the decode
    tokens/s MEAN to >= 0.95x unchunked. Token identity is asserted in
    main() — the chunk path replays the identical staircase-masked
    computation, so streams must not move at all."""
    from flexflow_tpu.serving import (
        ContinuousBatchingScheduler,
        ServeConfig,
        Telemetry,
        build_scheduler,
    )
    from flexflow_tpu.telemetry.slo import percentiles as _pcts

    model = _build_lm(layers, hidden, heads, vocab, max_seqs, max_len)
    chunk = max(8, max_len // 4)
    budget = max_seqs + chunk  # full decode reserve + one whole chunk
    long_rids = {i for i in range(num_requests) if i % 3 == 2}

    def admit_ttft(r):
        t_admit = next(t for t, e, _ in r.events if e == "admit")
        return r.first_token_time - t_admit

    def requests():
        return _hol_requests(vocab, max_len, num_requests)

    serve = ServeConfig(max_seqs=max_seqs, max_seq_len=max_len)
    _, engine, _ = build_scheduler(model, serve)
    modes = (
        ("unchunked", {}),
        ("chunked", dict(token_budget=budget, chunk_size=chunk)),
    )
    for _, kw in modes:  # full warm run: every jit width off the clock
        ContinuousBatchingScheduler(engine, **kw).run(requests())

    tps = {name: [] for name, _ in modes}
    ttft = {name: [] for name, _ in modes}
    itl = {name: [] for name, _ in modes}
    streams: dict = {}
    chunk_stats = None
    for _ in range(reps):  # interleaved: both modes see the same drift
        for name, kw in modes:
            tele = Telemetry(slo_window=8192)
            sched = ContinuousBatchingScheduler(
                engine, telemetry=tele, **kw
            )
            done = sched.run(requests())
            tps[name].append(sched.stats.tokens_per_s)
            long_admits = {
                r.admit_iter for r in done if r.rid in long_rids
            }
            ttft[name].extend(
                admit_ttft(r)
                for r in done
                if r.rid not in long_rids
                and r.ok
                and r.admit_iter in long_admits
            )
            itl[name].extend(tele.slo.itl_window.values().tolist())
            streams.setdefault(
                name, {r.rid: tuple(r.generated) for r in done}
            )
            if name == "chunked":
                chunk_stats = sched.stats
    if not ttft["chunked"] or not ttft["unchunked"]:
        raise SystemExit(
            "head-of-line stream produced no blocked shorts — the "
            "TTFT gate has nothing to measure"
        )
    mean_tps = {n_: sum(v) / len(v) for n_, v in tps.items()}
    ttft_p95 = {n_: _pcts(v, (95,))[95] for n_, v in ttft.items()}
    itl_p95 = {n_: _pcts(v, (95,))[95] for n_, v in itl.items()}
    matched = sum(
        1
        for rid in streams["unchunked"]
        if streams["chunked"].get(rid) == streams["unchunked"][rid]
    )
    ttft_ratio = ttft_p95["unchunked"] / ttft_p95["chunked"]
    s = chunk_stats
    return {
        "metric": f"serve_chunked_prefill_{layers}L_{hidden}h",
        "value": round(ttft_ratio, 3),
        "unit": "x_blocked_short_p95_ttft_vs_unchunked",
        # how much faster a short request behind a long prompt sees its
        # first token (acceptance floor: 1.3x; ITL gate rides along)
        "vs_baseline": round(ttft_ratio, 3),
        "token_budget": budget,
        "chunk_size": chunk,
        "reps": reps,
        "blocked_short_p95_ttft_ms": {
            n_: round(v * 1e3, 3) for n_, v in ttft_p95.items()
        },
        "ttft_p95_ratio": round(ttft_ratio, 3),
        "itl_p95_ms": {n_: round(v, 3) for n_, v in itl_p95.items()},
        "itl_p95_ratio": round(
            itl_p95["unchunked"] / itl_p95["chunked"], 3
        ),
        "chunked_tokens_per_s": round(mean_tps["chunked"], 2),
        "unchunked_tokens_per_s": round(mean_tps["unchunked"], 2),
        "throughput_ratio": round(
            mean_tps["chunked"] / mean_tps["unchunked"], 3
        ),
        "chunk_steps": s.chunk_steps,
        "chunk_tokens": s.chunk_tokens,
        "budget_deferrals": s.budget_deferrals,
        "streams_match": f"{matched}/{len(streams['unchunked'])}",
    }


def run_telemetry(
    layers: int,
    hidden: int,
    heads: int,
    vocab: int,
    max_seqs: int,
    max_len: int,
    num_requests: int,
    reps: int = 3,
):
    """Telemetry gate (writes BENCH_TELEMETRY.json), four assertions —
    each EXITS NONZERO on violation:

    1. **Overhead**: the async loop with NO telemetry attached (every
       instrument point short-circuits on one predicate) vs the same
       loop with the in-memory bundle (metrics + SLO windows, no file
       I/O) — interleaved-rep MEANS; the instrumented run must hold
       >= 0.98x (the <=2% overhead contract). The full-export config
       (trace + JSONL + text files) is measured and reported
       unguarded — per-iteration export cost is a user's explicit
       opt-in and scales with iteration granularity.
    2. **Token identity**: greedy streams identical across all three
       configs — observation must not perturb the system.
    3. **Artifacts**: the exported trace validates against the
       checked-in schema (spans nest, no negative durations) and SHOWS
       the double buffer — step N+1's in-flight window opens before
       step N's closes; metrics text and JSONL rows validate too.
    4. **Percentile agreement**: rolling-window p95 TTFT equals the
       post-hoc latency_percentiles p95 exactly (one shared
       implementation, window sized to hold every request)."""
    import tempfile

    from flexflow_tpu.serving import (
        AsyncContinuousBatchingScheduler,
        ServeConfig,
        Telemetry,
        build_scheduler,
        latency_percentiles,
    )
    from flexflow_tpu.telemetry import (
        validate_metrics_jsonl_file,
        validate_metrics_text,
        validate_trace_file,
    )

    model = _build_lm(layers, hidden, heads, vocab, max_seqs, max_len)

    def requests():
        return _mixed_requests(vocab, max_len, num_requests)

    serve = ServeConfig(max_seqs=max_seqs, max_seq_len=max_len,
                        serve_async=True)
    _, engine, _ = build_scheduler(model, serve)
    AsyncContinuousBatchingScheduler(engine).run(
        requests()[: max_seqs + 1]
    )  # warm jit signatures off the clock

    tmp = tempfile.mkdtemp(prefix="flexflow_telemetry_")
    paths = {
        "metrics_out": os.path.join(tmp, "metrics.prom"),
        "metrics_jsonl": os.path.join(tmp, "metrics.jsonl"),
        "trace": os.path.join(tmp, "trace.json"),
    }

    def make_tele(mode):
        if mode == "off":
            return None
        if mode == "on":  # in-memory metrics + SLO, no tracer, no I/O
            return Telemetry(slo_window=4 * num_requests)
        return Telemetry(slo_window=4 * num_requests, **paths)

    modes = ("off", "on", "full")
    tps = {m: [] for m in modes}
    streams: dict = {}
    last = {}
    for _ in range(reps):  # interleaved: all modes see the same drift
        for mode in modes:
            sched = AsyncContinuousBatchingScheduler(
                engine, telemetry=make_tele(mode)
            )
            done = sched.run(requests())
            tps[mode].append(sched.stats.tokens_per_s)
            streams.setdefault(
                mode, {r.rid: tuple(r.generated) for r in done}
            )
            last[mode] = (sched, done)
    mean = {m: sum(v) / len(v) for m, v in tps.items()}
    on_ratio = mean["on"] / mean["off"]
    full_ratio = mean["full"] / mean["off"]

    mismatched = [
        m
        for m in ("on", "full")
        if streams[m] != streams["off"]
    ]
    if mismatched:
        raise SystemExit(
            f"telemetry perturbed greedy streams in mode(s) {mismatched}"
        )

    # artifact validation (the full run wrote every format)
    trace_errs = validate_trace_file(paths["trace"], errors="list")
    metrics_errs = validate_metrics_text(
        open(paths["metrics_out"]).read(), errors="list"
    )
    jsonl_errs = validate_metrics_jsonl_file(
        paths["metrics_jsonl"], errors="list"
    )
    if trace_errs or metrics_errs or jsonl_errs:
        raise SystemExit(
            "telemetry artifacts failed schema validation: "
            f"{(trace_errs + metrics_errs + jsonl_errs)[:5]}"
        )

    # the double buffer must be VISIBLE: consecutive in-flight windows
    # overlap (dispatch N+1 inside window N)
    with open(paths["trace"]) as f:
        doc = json.load(f)
    windows = {
        e["args"]["step"]: (e["ts"], e["ts"] + e["dur"])
        for e in doc["traceEvents"]
        if e.get("ph") == "X" and e.get("name", "").startswith("inflight:")
    }
    overlapping = sum(
        1
        for n, (t0, t1) in windows.items()
        if n + 1 in windows and windows[n + 1][0] < t1
    )
    if not windows or overlapping == 0:
        raise SystemExit(
            f"async trace shows no overlapping in-flight windows "
            f"({overlapping}/{len(windows)})"
        )

    # rolling p95 TTFT == post-hoc percentile (shared implementation,
    # window holds every sample)
    sched_full, done_full = last["full"]
    post_p95_ms = (
        latency_percentiles(done_full, (95,), metric="ttft")[95] * 1e3
    )
    roll_p95_ms = sched_full.telemetry.slo.ttft_window.percentiles((95,))[95]
    if abs(post_p95_ms - roll_p95_ms) > 1e-6:
        raise SystemExit(
            f"rolling p95 TTFT {roll_p95_ms} != post-hoc {post_p95_ms}"
        )

    if on_ratio < 0.98:
        raise SystemExit(
            f"disabled->enabled telemetry overhead exceeds 2%: "
            f"{on_ratio:.3f}x"
        )
    # full export (trace spans + a JSONL row per iteration) is an
    # explicit opt-in whose cost scales with iteration GRANULARITY, not
    # load — reported, not gated: on the tiny smoke preset a ~1 ms
    # export tax against ~3 ms iterations reads as a huge ratio that
    # says nothing about a real model's step times

    return {
        "metric": f"serve_telemetry_{layers}L_{hidden}h",
        "value": round(mean["on"], 2),
        "unit": "tokens/s",
        # instrumented over uninstrumented mean throughput (gate: 0.98)
        "vs_baseline": round(on_ratio, 3),
        "off_tokens_per_s": round(mean["off"], 2),
        "full_export_tokens_per_s": round(mean["full"], 2),
        "full_export_ratio": round(full_ratio, 3),
        "reps": reps,
        "streams_match": f"{len(streams['off'])}/{len(streams['off'])}",
        "trace_events": len(doc["traceEvents"]),
        "inflight_windows": len(windows),
        "overlapping_windows": overlapping,
        "rolling_p95_ttft_ms": round(roll_p95_ms, 3),
        "post_hoc_p95_ttft_ms": round(post_p95_ms, 3),
        "slo": sched_full.telemetry.slo.snapshot(),
        "schema_validation": "ok",
    }


def run_chaos(
    layers: int,
    hidden: int,
    heads: int,
    vocab: int,
    max_seqs: int,
    max_len: int,
    num_requests: int,
    reps: int = 2,
    seed: int = 0,
    serve_async: bool = False,
):
    """Seeded chaos run: optimistic admission on a page pool sized to
    FORCE preemption, plus injected NaN logits, cancellations, latency
    spikes, and page steals. Success is not throughput — it is (a) every
    submitted rid reaching exactly one terminal status and (b) the page
    allocator's full accounting holding after every iteration. Either
    violation raises, which the CI step turns into a red build."""
    from flexflow_tpu.serving import (
        FaultInjector,
        FaultPlan,
        Request,
        ServeConfig,
        TERMINAL_STATUSES,
        build_scheduler,
    )

    model = _build_lm(layers, hidden, heads, vocab, max_seqs, max_len)
    page_size = max_len // 8
    # two simulated hosts, each holding HALF a max_len sequence of
    # pages: any single mixed request fits on one host (so every stream
    # can finish) but a host cannot hold two long continuations, so
    # optimistic admission forces preemption churn — and the host_down
    # site can reap a whole partition while the survivor progresses
    num_pages = max_len // page_size
    serve = ServeConfig(
        max_seqs=max_seqs,
        max_seq_len=max_len,
        kv_layout="paged",
        kv_page_size=page_size,
        kv_pages=num_pages,
        serve_hosts=2,
        admission="optimistic",
        max_preemptions=6,
        kv_swap=True,
        serve_async=serve_async,
        # exercise EVERY injector site: the n-gram draft gives the
        # draft-fault seam a target, and starting on the Pallas kernel
        # (interpret mode off-TPU) gives the kernel-fault seam one
        # dispatch to fail before the permanent dense fallback
        spec_draft="ngram",
        spec_k=2,
        decode_kernel="pallas",
        # in-memory telemetry: every injection must surface in the
        # exported metrics keyed by site (asserted below) — a fault the
        # observability layer can't see is a bug
        telemetry=True,
    )
    plan = FaultPlan(
        nan_rate=0.01,
        cancel_rate=0.005,
        spike_rate=0.05,
        spike_s=0.001,
        steal_iters=(4, 9),
        steal_pages=2,
        steal_hold=3,
        kernel_iters=(2,),
        draft_iters=(3,),
        # graceful-degradation sites: half the swap attempts in the
        # churn window fail (each must degrade to recompute, never a
        # lost request), and host 1 drops out mid-run then rejoins
        swap_fail_rate=0.5,
        host_down_iters={6: 1},
        host_down_hold=4,
    )
    injector = FaultInjector(plan, seed=seed)
    sched, engine, cache = build_scheduler(model, serve, injector=injector)
    # the cost decider correctly prices recompute below PCIe traffic on
    # a model this small; force always-swap so the swap_fail site and
    # the swap-restore path are actually exercised
    sched.swap_decider = None
    requests = _mixed_requests(vocab, max_len, num_requests)
    # a few requests carry deadlines the spikes may push past
    for r in requests[:: max(1, num_requests // 4)]:
        r.deadline_s = 30.0
    for r in requests:
        sched.submit(r, strict=False)
    import time as _time

    t0 = _time.perf_counter()
    # the async loop also drains its in-flight pipeline; invariants are
    # probed INSIDE the in-flight window every iteration (pinned pages
    # are part of the accounting, not an exemption)
    while sched._work_pending():
        sched.step()
        cache.check_invariants(extra_free=injector.stolen_pages)
    sched.stats.elapsed_s += _time.perf_counter() - t0
    injector.release_stolen_pages(cache)
    cache.check_invariants()

    s = sched.stats
    by_status = {}
    for r in sched.finished:
        by_status[r.status] = by_status.get(r.status, 0) + 1
    lost = [
        r.rid
        for r in requests
        if r.status not in TERMINAL_STATUSES
    ]
    if lost:
        raise SystemExit(f"chaos run LOST requests (no terminal status): {lost}")
    if s.terminal_requests != s.submitted_requests:
        raise SystemExit(
            f"terminal accounting mismatch: {s.terminal_requests} terminal "
            f"!= {s.submitted_requests} submitted"
        )
    # observability gate: EVERY fault the injector fired — NaN, kernel,
    # draft, steal, cancel, spike — must appear in the exported metrics
    # with the same count, keyed by site
    injected = injector.summary()
    for site in ("kernel", "draft", "page_steal", "swap_fail", "host_down"):
        if site not in injected:
            raise SystemExit(
                f"chaos plan scheduled a {site!r} fault that never fired "
                f"(injected: {injected})"
            )
    metrics_text = sched.telemetry.render_prometheus()
    unseen = [
        site
        for site, n in injected.items()
        if f'serve_fault_injections_total{{site="{site}"}} {n}'
        not in metrics_text
    ]
    if unseen:
        raise SystemExit(
            f"injected faults missing from exported metrics: {unseen} "
            f"(injected: {injected})"
        )
    return {
        "metric": f"serve_chaos_{layers}L_{hidden}h"
        + ("_async" if serve_async else ""),
        "serve_async": serve_async,
        # goodput under faults: tokens of successfully FINISHED requests
        "value": round(s.goodput_tokens_per_s, 2),
        "unit": "goodput_tokens/s",
        # fraction of submitted requests that FINISHED under chaos
        "vs_baseline": round(s.finished_requests / s.submitted_requests, 3),
        "seed": seed,
        "admission": "optimistic",
        "page_size": page_size,
        "num_pages": num_pages,
        "submitted": s.submitted_requests,
        "by_status": by_status,
        "preemptions": s.preemptions,
        "peak_in_flight": s.peak_in_flight,
        "swap_outs": s.swap_outs,
        "swap_ins": s.swap_ins,
        "host_downs": s.host_downs,
        "injected": injector.summary(),
        "injected_in_metrics": True,
        "kernel_fallbacks": engine.kernel_fallbacks,
        "lost_requests": 0,
        "invariant_violations": 0,
        "tokens_per_s": round(s.tokens_per_s, 2),
    }


def run_recovery(
    layers: int,
    hidden: int,
    heads: int,
    vocab: int,
    max_seqs: int,
    max_len: int,
    num_requests: int,
    seed: int = 0,
):
    """Durable-serving gate (writes BENCH_RECOVERY.json): crash a
    journaled run at the WORST phase (this iteration's tokens emitted,
    the commit flush not yet run), restart a fresh engine from the
    write-ahead journal, and measure MTTR — crash to first post-restart
    committed token — broken down into journal fold, engine rebuild +
    re-admission, and recompute-to-cursor. Hard gates, EXIT NONZERO on
    miss: the crash actually fired mid-run, zero lost requests, and
    every final stream token-identical to the fault-free baseline
    (which is simultaneously the zero-duplicates and zero-gaps proof —
    replayed history plus resumed decode reproduce the exact
    sequence)."""
    import tempfile
    import time as _time

    from flexflow_tpu.serving import (
        FaultInjector,
        FaultPlan,
        ProcessCrash,
        ServeConfig,
        build_scheduler,
        readmit,
        recover_journal,
    )

    model = _build_lm(layers, hidden, heads, vocab, max_seqs, max_len)
    page_size = max(4, max_len // 8)

    def _serve(journal=""):
        return ServeConfig(
            max_seqs=max_seqs,
            max_seq_len=max_len,
            kv_layout="paged",
            kv_page_size=page_size,
            journal=journal,
            journal_fsync="batch",
        )

    # fault-free reference streams (and the jit warm-up, so the MTTR
    # below prices recovery work, not first-compile)
    ref_sched, _, _ = build_scheduler(model, _serve())
    for r in _mixed_requests(vocab, max_len, num_requests):
        ref_sched.submit(r, strict=False)
    ref = {r.rid: list(r.generated) for r in ref_sched.run()}

    wal = os.path.join(tempfile.mkdtemp(prefix="ff_recovery_"), "serve.wal")
    crash_iter = 6  # deep enough for finished + live + queued requests
    injector = FaultInjector(
        FaultPlan(crash_iters={crash_iter: "commit"}), seed=seed
    )
    sched, _, _ = build_scheduler(model, _serve(wal), injector=injector)
    for r in _mixed_requests(vocab, max_len, num_requests):
        sched.submit(r, strict=False)
    crashed = False
    try:
        while sched.queue or sched.running:
            sched.step()
    except ProcessCrash:
        crashed = True
    t_crash = _time.perf_counter()
    if not crashed:
        raise SystemExit(
            f"recovery bench mis-aimed: run finished before the planned "
            f"crash at iteration {crash_iter}"
        )

    state = recover_journal(wal)
    t_folded = _time.perf_counter()
    sched2, _, _ = build_scheduler(model, _serve(wal))
    resubmitted, completed = readmit(sched2, state)
    t_readmit = _time.perf_counter()
    cursors = {r.rid: len(r.generated) for r in resubmitted}
    t_first = None
    while sched2.queue or sched2.running:
        sched2.step()
        if t_first is None and any(
            len(r.generated) > cursors[r.rid] for r in resubmitted
        ):
            t_first = _time.perf_counter()
    t_first = t_first or _time.perf_counter()

    final = {int(r): list(t["tokens"]) for r, t in state.terminals.items()}
    for req in resubmitted + completed:
        final[req.rid] = [int(t) for t in req.generated]
    lost = [rid for rid in ref if rid not in final]
    if lost:
        raise SystemExit(f"recovery lost requests: {sorted(lost)}")
    mismatched = [rid for rid in ref if final[rid] != ref[rid]]
    if mismatched:
        raise SystemExit(
            f"recovered streams diverged from the fault-free baseline "
            f"for rids {sorted(mismatched)} — duplicated or dropped "
            f"published tokens"
        )
    mttr_s = t_first - t_crash
    return {
        "metric": f"serve_recovery_{layers}L_{hidden}h",
        "value": round(mttr_s * 1e3, 3),
        "unit": "mttr_ms",
        "seed": seed,
        "fsync": "batch",
        "crash_iteration": crash_iter,
        "crash_phase": "commit",
        "num_requests": num_requests,
        "finished_before_crash": len(state.terminals),
        "recovered_live": len(resubmitted) + len(completed),
        "replayed_tokens": state.replayed_tokens,
        "journal_records": state.records,
        "journal_bytes": os.path.getsize(wal),
        "torn_records": state.torn,
        "mttr_breakdown_ms": {
            "fold_journal": round((t_folded - t_crash) * 1e3, 3),
            "rebuild_and_readmit": round((t_readmit - t_folded) * 1e3, 3),
            "recompute_to_cursor": round((t_first - t_readmit) * 1e3, 3),
        },
        "lost_requests": 0,
        "duplicated_tokens": 0,
        "streams_match": f"{len(ref)}/{len(ref)}",
    }


def run_pressure(
    layers: int,
    hidden: int,
    heads: int,
    vocab: int,
    max_seqs: int,
    max_len: int,
    num_requests: int,
    seed: int = 0,
):
    """Graceful-degradation gate: long-prompt streams on a page pool
    too small for two of them, so every boundary crossing preempts a
    victim. Recompute-only re-admission re-prefills the whole resumed
    sequence; swap-to-host restores the committed pages from host
    staging instead. The gates are (a) swap-enabled goodput >= 1.3x
    recompute-only on BOTH loops, (b) every restored stream
    token-identical to an unpressured reference, and (c) zero lost
    requests under combined chaos (pool pressure + swap_fail +
    host_down) — again on both loops."""
    from flexflow_tpu.serving import (
        FaultInjector,
        FaultPlan,
        Request,
        ServeConfig,
        build_scheduler,
    )
    import time as _time

    page_size = max_len // 8
    # long prompts ending two tokens shy of a page boundary with a
    # short decode tail: every stream crosses into a fresh page at its
    # ~3rd generated token, so a tight pool collides immediately, and
    # re-prefill (O(len^2) attention over ~7/8 of max_len) dominates
    # recompute-only re-admission while the decode work both policies
    # share stays small
    prompt_pages = 7
    prompt_len = prompt_pages * page_size - 2
    max_new = 8
    footprint = -(-(prompt_len + max_new) // page_size)  # pages/request

    def _requests():
        return [
            Request(
                rid=i,
                prompt=[(i * 11 + j) % vocab for j in range(prompt_len)],
                max_new_tokens=max_new,
            )
            for i in range(num_requests)
        ]

    # ONE model for the reference and both timed legs: the jit caches
    # (prefill buckets, decode step) stay shared, so the timed legs
    # compare scheduling policy, not compilation luck. The chaos legs
    # get a SEPARATE model: compile_for_serving(serve_hosts=2) pins a
    # two-host placement on the model, and a later single-host
    # build_scheduler would silently inherit it (explicit placement
    # wins by design), splitting the tight pool in half
    model = _build_lm(layers, hidden, heads, vocab, max_seqs, max_len)
    chaos_model = _build_lm(layers, hidden, heads, vocab, max_seqs, max_len)

    def _run_leg(serve, plan=None, force_swap=False, check=False, lm=None):
        injector = FaultInjector(plan, seed=seed) if plan is not None else None
        sched, _, cache = build_scheduler(
            lm if lm is not None else model, serve, injector=injector
        )
        if force_swap:
            # the cost decider honestly prices recompute below PCIe
            # traffic on a benchmark-sized model; the point here is to
            # measure the swap path, so always-swap
            sched.swap_decider = None
        for r in _requests():
            sched.submit(r)
        t0 = _time.perf_counter()
        while sched._work_pending():
            sched.step()
            if check:
                cache.check_invariants(
                    extra_free=injector.stolen_pages if injector else 0
                )
        sched.stats.elapsed_s += _time.perf_counter() - t0
        cache.check_invariants()
        return sched

    # unpressured reference: ample pool, no swap — the token streams
    # every pressured leg must reproduce exactly (greedy decoding)
    ample = ServeConfig(
        max_seqs=max_seqs,
        max_seq_len=max_len,
        kv_layout="paged",
        kv_page_size=page_size,
        kv_pages=max_seqs * (max_len // page_size),
    )
    ref_sched = _run_leg(ample)
    ref = {r.rid: tuple(r.generated) for r in ref_sched.finished}
    assert len(ref) == num_requests

    def _check_streams(sched, leg):
        got = {r.rid: tuple(r.generated) for r in sched.finished}
        # the only faults in any pressure leg are recoverable ones
        # (pool pressure, swap_fail, host_down), so "zero lost" here
        # means stronger than terminal: every rid must FINISH
        not_finished = [
            r.rid for r in sched.finished if r.status != "finished"
        ]
        if len(got) != num_requests or not_finished:
            raise SystemExit(
                f"pressure {leg} LOST requests: {len(got)}/{num_requests} "
                f"terminal, not finished: {not_finished}"
            )
        bad = [rid for rid, toks in got.items() if toks != ref.get(rid)]
        if bad:
            raise SystemExit(
                f"pressure {leg} moved greedy streams for rids {bad}"
            )
        return len(got)

    # a pool that admits TWO long prompts but cannot hold their decode
    # growth: optimistic admission overcommits, and every page-boundary
    # crossing preempts the younger stream
    tight_pages = 2 * prompt_pages

    # untimed warm-up of the swap path: the page-scatter restore
    # kernels compile per page-count, and the timed legs compare
    # steady-state policies, not first-call XLA compilation
    _run_leg(
        ServeConfig(
            max_seqs=max_seqs,
            max_seq_len=max_len,
            kv_layout="paged",
            kv_page_size=page_size,
            kv_pages=tight_pages,
            admission="optimistic",
            max_preemptions=64,
            kv_swap=True,
        ),
        force_swap=True,
    )

    loops = {}
    for serve_async in (False, True):
        tag = "async" if serve_async else "sync"
        common = dict(
            max_seqs=max_seqs,
            max_seq_len=max_len,
            kv_layout="paged",
            kv_page_size=page_size,
            kv_pages=tight_pages,
            admission="optimistic",
            max_preemptions=64,
            serve_async=serve_async,
        )
        rec = _run_leg(ServeConfig(**common))
        _check_streams(rec, f"{tag}/recompute")
        swp = _run_leg(
            ServeConfig(**common, kv_swap=True), force_swap=True
        )
        _check_streams(swp, f"{tag}/swap")
        if swp.stats.swap_outs == 0:
            raise SystemExit(
                f"pressure {tag}/swap never swapped — the leg measured "
                f"nothing (preemptions {swp.stats.preemptions})"
            )
        ratio = (
            swp.stats.goodput_tokens_per_s / rec.stats.goodput_tokens_per_s
        )

        # combined chaos on two hosts: pool pressure + seeded swap
        # failures + a host partition dropping mid-run and rejoining.
        # Each host gets the same tight two-prompts-collide pool the
        # timed legs use (pool pressure -> swap attempts for the
        # swap_fail site to hit), and any single request still fits
        chaos_pages = 2 * tight_pages
        chaos = _run_leg(
            ServeConfig(
                max_seqs=max_seqs,
                max_seq_len=max_len,
                kv_layout="paged",
                kv_page_size=page_size,
                kv_pages=chaos_pages,
                serve_hosts=2,
                admission="optimistic",
                max_preemptions=64,
                kv_swap=True,
                serve_async=serve_async,
                telemetry=True,
            ),
            plan=FaultPlan(
                swap_fail_rate=0.4,
                host_down_iters={8: 1},
                host_down_hold=6,
            ),
            force_swap=True,
            check=True,
            lm=chaos_model,
        )
        _check_streams(chaos, f"{tag}/chaos")
        injected = chaos.injector.summary()
        missing = [s for s in ("host_down", "swap_fail") if s not in injected]
        if missing:
            raise SystemExit(
                f"pressure {tag}/chaos: {missing} never fired ({injected})"
            )
        loops[tag] = {
            "goodput_recompute": round(rec.stats.goodput_tokens_per_s, 2),
            "goodput_swap": round(swp.stats.goodput_tokens_per_s, 2),
            "ratio": round(ratio, 3),
            "preemptions_recompute": rec.stats.preemptions,
            "preemptions_swap": swp.stats.preemptions,
            "swap_outs": swp.stats.swap_outs,
            "swap_ins": swp.stats.swap_ins,
            "swap_bytes": swp.stats.swap_bytes,
            "chaos_injected": injected,
            "chaos_host_downs": chaos.stats.host_downs,
            "chaos_finished": chaos.stats.finished_requests,
            "streams_match": f"{num_requests}/{num_requests}",
        }

    return {
        "metric": f"serve_pressure_{layers}L_{hidden}h",
        "value": min(l["ratio"] for l in loops.values()),
        "unit": "x_goodput_swap_vs_recompute",
        "vs_baseline": min(l["ratio"] for l in loops.values()),
        "page_size": page_size,
        "prompt_len": prompt_len,
        "max_new": max_new,
        "tight_pages": tight_pages,
        "num_requests": num_requests,
        "seed": seed,
        "lost_requests": 0,
        "sync": loops["sync"],
        "async": loops["async"],
    }


def run_frontdoor(
    layers: int,
    hidden: int,
    heads: int,
    vocab: int,
    max_seqs: int,
    max_len: int,
    num_requests: int,
    seed: int = 0,
):
    """Disaggregated front door gate (--frontdoor): open-loop seeded
    Poisson arrivals with heavy-tailed prompt lengths against (a) the
    monolithic chunked engine and (b) the prefill→decode
    DisaggregatedPipeline, then (c) a 2-replica router chaos leg.

    Simulation posture: both tiers interleave in ONE process, so
    wall-clock inter-token gaps would charge the decode tier for
    prefill steps it no longer runs. Decode ITL is therefore measured
    on a decode-tier-only clock — the monolithic leg's clock is its
    full step time (its one engine IS its decode engine, chunk work
    included: exactly the interference disaggregation removes), the
    pipeline's is `decode_step_s`. Goodput is wall-clock, with the
    pipeline credited for the tier overlap a two-box deployment hides —
    bounded by the smaller tier's clock, so the credit is conservative
    (true concurrent overlap is at least zero and at most that min).
    Greedy streams must be
    token-identical across all legs — the handoff restores committed
    pages bit-exactly, so logits cannot move."""
    import numpy as np

    from flexflow_tpu.serving import (
        FaultInjector,
        FaultPlan,
        Request,
        ServeConfig,
        build_scheduler,
    )
    from flexflow_tpu.serving.frontend import (
        DisaggregatedPipeline,
        ReplicaRouter,
    )
    from flexflow_tpu.telemetry.slo import percentiles as _pcts
    import time as _time

    rng = np.random.default_rng(seed)
    page_size = max(4, max_len // 16)
    chunk = 8  # multiple of 8 (decode_kernel='auto' constraint)
    budget = max_seqs + chunk  # full decode reserve + one whole chunk
    max_new = max(6, max_len // 8)
    prompts = _heavy_tailed_prompts(vocab, max_len - max_new, num_requests, rng)
    arrivals = _poisson_arrivals(num_requests, rate=num_requests * 4.0, rng=rng)

    serve = ServeConfig(
        max_seqs=max_seqs,
        max_seq_len=max_len,
        kv_layout="paged",
        kv_page_size=page_size,
        kv_pages=max_seqs * (max_len // page_size) + 8,
        token_budget=budget,
        chunk_size=chunk,
    )
    model = _build_lm(layers, hidden, heads, vocab, max_seqs, max_len)

    def requests():
        return [
            Request(rid=i, prompt=list(p), max_new_tokens=max_new)
            for i, p in enumerate(prompts)
        ]

    def _drive(backend):
        """Open-loop driver: submit each request at its arrival offset,
        step whenever work is pending, and attribute every inter-token
        gap to the backend's decode clock via publish-cursor diffs (the
        front-door server's own fan-out pattern). The first token of a
        stream is TTFT, never ITL; tokens landing in one publish share
        the interval evenly."""
        reqs = requests()
        pending = list(range(len(reqs)))
        seen = {r.rid: 0 for r in reqs}
        last_clk = {}
        itl = []
        is_pipe = hasattr(backend, "decode_step_s")
        work_pending = getattr(
            backend, "work_pending", None
        ) or backend._work_pending
        step_clock = 0.0
        t0 = _time.perf_counter()
        while pending or work_pending():
            now = _time.perf_counter() - t0
            while pending and arrivals[pending[0]] <= now:
                backend.submit(reqs[pending.pop(0)])
            if not work_pending():
                if pending:
                    _time.sleep(
                        max(0.0, arrivals[pending[0]] - (now))
                    )
                continue
            if is_pipe:
                backend.step()
                clk = backend.decode_step_s
            else:
                t1 = _time.perf_counter()
                backend.step()
                step_clock += _time.perf_counter() - t1
                clk = step_clock
            for r in reqs:
                fresh = len(r.generated) - seen[r.rid]
                if fresh <= 0:
                    continue
                if seen[r.rid] >= 1:
                    itl.extend([(clk - last_clk[r.rid]) / fresh] * fresh)
                last_clk[r.rid] = clk
                seen[r.rid] += fresh
        elapsed = _time.perf_counter() - t0
        if is_pipe:
            # the in-process interleaving pays for both tiers
            # SEQUENTIALLY; a two-box deployment overlaps them, and the
            # hidden time is bounded by the smaller tier's clock —
            # credit exactly that back (conservative: true overlap can
            # only be larger than zero and is capped by min)
            elapsed -= min(backend.prefill_step_s, backend.decode_step_s)
        done = {r.rid: tuple(r.generated) for r in reqs}
        lost = [r.rid for r in reqs if r.status != "finished"]
        tokens = sum(len(t) for t in done.values())
        return {
            "streams": done,
            "lost": lost,
            "itl": itl,
            "ttft": [r.ttft_s for r in reqs if r.ok],
            "goodput": tokens / elapsed if elapsed else 0.0,
            "elapsed_s": elapsed,
        }

    # untimed warm-up: every prefill bucket / chunk width / decode step
    # jit-compiles off the clock, on BOTH engine shapes
    build_scheduler(model, serve)[0].run(requests())
    DisaggregatedPipeline(model, model, serve).run(requests())

    mono = _drive(build_scheduler(model, serve)[0])
    pipe = DisaggregatedPipeline(model, model, serve)
    disagg = _drive(pipe)

    for leg, res in (("monolithic", mono), ("disaggregated", disagg)):
        if res["lost"]:
            raise SystemExit(f"frontdoor {leg} lost requests: {res['lost']}")
    moved = [
        rid
        for rid in mono["streams"]
        if disagg["streams"][rid] != mono["streams"][rid]
    ]
    if moved:
        raise SystemExit(
            f"frontdoor: disaggregation moved greedy streams {moved}"
        )
    if pipe.handoffs == 0:
        raise SystemExit("frontdoor: no stream ever crossed the tiers")

    # chaos leg: two weight-identical replicas, a seeded kill
    # mid-stream, closed loop (the drain contract is the point here)
    injector = FaultInjector(
        FaultPlan(replica_down_iters={4: 1}), seed=seed
    )
    import dataclasses as _dc

    router = ReplicaRouter(
        [model, model],
        _dc.replace(serve, telemetry=True),
        injector=injector,
    )
    chaos_reqs = requests()
    chaos_done = router.run(chaos_reqs)
    chaos_lost = [r.rid for r in chaos_reqs if r.status != "finished"]
    if len(chaos_done) != num_requests or chaos_lost:
        raise SystemExit(
            f"frontdoor chaos LOST requests: {len(chaos_done)}/"
            f"{num_requests} terminal, not finished: {chaos_lost}"
        )
    chaos_moved = [
        r.rid
        for r in chaos_reqs
        if tuple(r.generated) != mono["streams"][r.rid]
    ]
    if chaos_moved:
        raise SystemExit(
            f"frontdoor chaos moved greedy streams {chaos_moved}"
        )
    if injector.injected["replica_down"] != 1 or router.rerouted == 0:
        raise SystemExit(
            f"frontdoor chaos never exercised the kill "
            f"(injected {dict(injector.injected)}, "
            f"rerouted {router.rerouted})"
        )
    metrics = router.telemetry.registry.render_prometheus()
    for series in (
        "serve_router_replica_down_total",
        "serve_router_reroute_total",
        "serve_router_requests_total",
    ):
        if series not in metrics:
            raise SystemExit(
                f"frontdoor chaos: {series} missing from telemetry"
            )

    itl_p99 = {
        "monolithic": _pcts(mono["itl"], (99,))[99],
        "disaggregated": _pcts(disagg["itl"], (99,))[99],
    }
    ttft_p99 = {
        "monolithic": _pcts(mono["ttft"], (99,))[99],
        "disaggregated": _pcts(disagg["ttft"], (99,))[99],
    }
    itl_ratio = (
        itl_p99["monolithic"] / itl_p99["disaggregated"]
        if itl_p99["disaggregated"]
        else 0.0
    )
    goodput_ratio = (
        disagg["goodput"] / mono["goodput"] if mono["goodput"] else 0.0
    )
    return {
        "metric": f"serve_frontdoor_{layers}L_{hidden}h",
        "value": round(itl_ratio, 3),
        "unit": "x_p99_decode_itl_vs_monolithic",
        "vs_baseline": round(itl_ratio, 3),
        "seed": seed,
        "num_requests": num_requests,
        "page_size": page_size,
        "chunk_size": chunk,
        "token_budget": budget,
        "max_new": max_new,
        "prompt_lens": [len(p) for p in prompts],
        "p99_decode_itl_ms": {
            n_: round(v * 1e3, 3) for n_, v in itl_p99.items()
        },
        "itl_p99_ratio": round(itl_ratio, 3),
        "p99_ttft_ms": {
            n_: round(v * 1e3, 3) for n_, v in ttft_p99.items()
        },
        "goodput_tokens_per_s": {
            "monolithic": round(mono["goodput"], 2),
            "disaggregated": round(disagg["goodput"], 2),
        },
        "goodput_ratio": round(goodput_ratio, 3),
        "handoffs": pipe.handoffs,
        "handoff_fallbacks": pipe.handoff_fallbacks,
        "handoff_bytes": pipe.handoff_bytes,
        "chaos": {
            "replica_downs": injector.injected["replica_down"],
            "rerouted": router.rerouted,
            "lost_requests": 0,
            "streams_match": f"{num_requests}/{num_requests}",
        },
        "streams_match": f"{num_requests}/{num_requests}",
    }


def run_tenancy(
    layers: int,
    hidden: int,
    heads: int,
    vocab: int,
    max_seqs: int,
    max_len: int,
    num_requests: int,
    seed: int = 0,
):
    """Multi-tenant gate (writes BENCH_TENANCY.json): mixed-priority,
    mixed-adapter OPEN-LOOP Poisson traffic at >= 2x overload (the
    whole stream arrives in a burst against a slot pool half its size),
    weighted-fair deficit-round-robin scheduling (gold:4, bronze:1)
    vs the unweighted FIFO planner on the SAME arrival schedule.
    Requests rotate across LoRA adapters 0 / 1 / none, so the fairness
    legs also exercise the per-slot adapter gather under preemption
    pressure. Gates — EXIT NONZERO on miss: (a) gold-class p95 TTFT
    SLO attainment under weighted-fair >= the FIFO leg's (the
    threshold is the pooled median TTFT of both legs, so it always
    discriminates), (b) bronze is starvation-bounded — every bronze
    request finishes and its weighted-leg p95 TTFT stays within 10x
    the FIFO leg's, (c) zero lost requests on every leg, and (d)
    every stream is token-identical to an uncontended isolated
    reference run (fairness reorders WHEN work is granted, never WHAT
    is computed — including the adapter deltas)."""
    import numpy as np

    from flexflow_tpu.serving import Request, ServeConfig, build_scheduler
    from flexflow_tpu.serving.tenancy import make_lora_weights
    from flexflow_tpu.serving.tenancy.slo import class_slo_snapshot
    import time as _time

    rng = np.random.default_rng(seed)
    chunk = 8
    budget = max_seqs + chunk
    max_new = max(6, max_len // 8)
    classes = "gold:4,bronze:1"
    n = num_requests
    # whole stream inside a tight burst: with the slot pool at half the
    # request count the queue is >= 2x oversubscribed from the start
    arrivals = _poisson_arrivals(n, rate=n * 16.0, rng=rng)
    prompt_lens = [4 + int(rng.integers(0, max_len // 4)) for _ in range(n)]

    def requests(with_class):
        out = []
        for i in range(n):
            out.append(
                Request(
                    rid=i,
                    prompt=[(i * 7 + j) % vocab
                            for j in range(prompt_lens[i])],
                    max_new_tokens=max_new,
                    priority_class=(
                        ("gold" if i % 2 == 0 else "bronze")
                        if with_class else ""
                    ),
                    tenant="acme" if i % 2 == 0 else "initech",
                    adapter_id=(0, 1, -1)[i % 3],
                )
            )
        return out

    def _serve(**kw):
        return ServeConfig(
            max_seqs=max_seqs,
            max_seq_len=max_len,
            kv_layout="paged",
            token_budget=budget,
            chunk_size=chunk,
            adapters=2,
            adapter_rank=4,
            **kw,
        )

    model = _build_lm(layers, hidden, heads, vocab, max_seqs, max_len)

    def _build(serve):
        sched, engine, _ = build_scheduler(model, serve)
        for aid in (0, 1):
            engine.adapters.load(
                aid, make_lora_weights(engine.adapters.spec, 4, seed=aid)
            )
        return sched

    def _drive(sched, reqs):
        """Open-loop: submit each request at its arrival offset, step
        while work is pending, read TTFT off the request records."""
        pending = list(range(len(reqs)))
        t0 = _time.perf_counter()
        while pending or sched._work_pending():
            now = _time.perf_counter() - t0
            while pending and arrivals[pending[0]] <= now:
                sched.submit(reqs[pending.pop(0)])
            if not sched._work_pending():
                if pending:
                    _time.sleep(max(0.0, arrivals[pending[0]] - now))
                continue
            sched.step()
        elapsed = _time.perf_counter() - t0
        lost = [r.rid for r in reqs if r.status != "finished"]
        return {
            "streams": {r.rid: tuple(r.generated) for r in reqs},
            "lost": lost,
            "ttft": {r.rid: r.ttft_s for r in reqs if r.ok},
            "elapsed_s": elapsed,
        }

    # uncontended isolated reference: every request gets a slot at t0 —
    # the token streams both timed legs must reproduce exactly
    ref_sched = _build(
        ServeConfig(max_seqs=n, max_seq_len=max_len, kv_layout="paged",
                    adapters=2, adapter_rank=4, classes=classes)
    )
    ref_reqs = requests(with_class=True)
    ref_sched.run(ref_reqs)
    ref = {r.rid: tuple(r.generated) for r in ref_reqs}
    if len(ref) != n or any(r.status != "finished" for r in ref_reqs):
        raise SystemExit("tenancy reference leg lost requests")

    # untimed warm-up of the contended geometry (jit off the clock)
    _build(_serve(classes=classes, telemetry=True)).run(
        requests(with_class=True)
    )

    legs = {}
    for tag, kw, with_class in (
        ("weighted", dict(classes=classes, telemetry=True), True),
        ("fifo", dict(), False),
    ):
        sched = _build(_serve(**kw))
        res = _drive(sched, requests(with_class))
        if res["lost"]:
            raise SystemExit(f"tenancy {tag} leg LOST requests: "
                             f"{res['lost']}")
        moved = [rid for rid, t in res["streams"].items()
                 if t != ref[rid]]
        if moved:
            raise SystemExit(
                f"tenancy {tag} leg moved greedy streams for rids "
                f"{moved} — fairness must not change WHAT is computed"
            )
        res["sched"] = sched
        legs[tag] = res

    gold = [i for i in range(n) if i % 2 == 0]
    bronze = [i for i in range(n) if i % 2 == 1]

    def _p(ttfts, q):
        xs = sorted(ttfts)
        return xs[min(len(xs) - 1, int(q * len(xs)))] if xs else 0.0

    # load-derived SLO threshold: the pooled median TTFT of both legs
    # always splits the distribution, so attainment discriminates on
    # any machine speed
    pooled = [t for leg in legs.values() for t in leg["ttft"].values()]
    slo_s = _p(pooled, 0.5)

    def _attain(leg, rids):
        ts = [legs[leg]["ttft"][r] for r in rids]
        return sum(t <= slo_s for t in ts) / len(ts)

    att = {
        "threshold_ms": round(slo_s * 1e3, 2),
        "gold_weighted": round(_attain("weighted", gold), 3),
        "gold_fifo": round(_attain("fifo", gold), 3),
        "bronze_weighted": round(_attain("weighted", bronze), 3),
        "bronze_fifo": round(_attain("fifo", bronze), 3),
    }
    if att["gold_weighted"] < att["gold_fifo"]:
        raise SystemExit(
            f"tenancy gate: gold SLO attainment under weighted-fair "
            f"({att['gold_weighted']}) fell below FIFO "
            f"({att['gold_fifo']}) at threshold {att['threshold_ms']}ms"
        )
    bz_w = _p([legs["weighted"]["ttft"][r] for r in bronze], 0.95)
    bz_f = _p([legs["fifo"]["ttft"][r] for r in bronze], 0.95)
    if bz_f > 0 and bz_w > 10.0 * bz_f:
        raise SystemExit(
            f"tenancy gate: bronze p95 TTFT {bz_w * 1e3:.1f}ms exceeds "
            f"10x the FIFO leg's {bz_f * 1e3:.1f}ms — starvation is "
            "unbounded"
        )

    wsched = legs["weighted"]["sched"]
    gold_w = _p([legs["weighted"]["ttft"][r] for r in gold], 0.95)
    gold_f = _p([legs["fifo"]["ttft"][r] for r in gold], 0.95)
    return {
        "metric": f"serve_tenancy_{layers}L_{hidden}h_gold_p95_ttft",
        "value": round(gold_w * 1e3, 2),
        "unit": "ms",
        # FIFO gold p95 TTFT over weighted-fair's (>1 = priority win)
        "vs_baseline": round(gold_f / gold_w, 3) if gold_w else 0.0,
        "classes": classes,
        "overload": f"{n} requests / {max_seqs} slots",
        "ttft_ms": {
            leg: {
                "gold_p50": round(_p([legs[leg]["ttft"][r]
                                      for r in gold], 0.5) * 1e3, 2),
                "gold_p95": round(_p([legs[leg]["ttft"][r]
                                      for r in gold], 0.95) * 1e3, 2),
                "bronze_p95": round(_p([legs[leg]["ttft"][r]
                                        for r in bronze], 0.95) * 1e3, 2),
            }
            for leg in legs
        },
        "slo_attainment": att,
        "lost_requests": 0,
        "streams_match": f"{n}/{n}",
        "adapter_pool": wsched.adapters.telemetry_gauges(),
        "adapter_traffic": wsched.adapters.telemetry_counters(),
        "per_class_slo": class_slo_snapshot(wsched._class_slo),
    }


_PRESETS = {
    # flagship geometry (transformer.cc:79-85) as a decoder LM — the TPU
    # target; CPU CI uses --smoke
    "flagship": dict(
        layers=12, hidden=1024, heads=16, vocab=32000,
        max_seqs=8, max_len=512, num_requests=32,
    ),
    # mid-size config a CPU box can measure in minutes — the recorded
    # BENCH_SERVE.json numbers come from here when no TPU is attached
    "medium": dict(
        layers=4, hidden=256, heads=8, vocab=2048,
        max_seqs=4, max_len=128, num_requests=16,
    ),
    "smoke": dict(
        layers=2, hidden=64, heads=4, vocab=128,
        max_seqs=4, max_len=64, num_requests=8,
    ),
}


def main():
    sys.path.insert(0, __file__.rsplit("/", 1)[0])
    args = dict(_PRESETS["flagship"])
    mode = "default"
    spec_k = 4
    spec_branch = 3
    seed = 0
    decode_kernel = "pallas"
    serve_async = False
    argv = sys.argv[1:]
    i = 0
    while i < len(argv):
        a = argv[i]
        if a == "--smoke":
            args = dict(_PRESETS["smoke"])
        elif a == "--paged":
            mode = "paged"
        elif a == "--spec":
            mode = "spec"
        elif a == "--spec-tree":
            mode = "spec_tree"
        elif a == "--chaos":
            mode = "chaos"
        elif a == "--recovery":
            mode = "recovery"
        elif a == "--pressure":
            mode = "pressure"
        elif a == "--frontdoor":
            mode = "frontdoor"
        elif a == "--chunked":
            mode = "chunked"
        elif a == "--prefix":
            mode = "prefix"
        elif a == "--pod":
            mode = "pod"
        elif a == "--telemetry":
            mode = "telemetry"
        elif a == "--multistep":
            mode = "multistep"
        elif a == "--tenancy":
            mode = "tenancy"
        elif a == "--serve-async":
            # alone: the sync-vs-async comparison (BENCH_ASYNC.json);
            # with --chaos: the chaos gate runs the async loop
            serve_async = True
        elif a == "--seed":
            i += 1
            seed = int(argv[i])
        elif a == "--decode-kernel":
            mode = "decode_kernel"
            i += 1
            decode_kernel = argv[i]
        elif a == "--spec-k":
            i += 1
            spec_k = int(argv[i])
        elif a == "--spec-branch":
            i += 1
            spec_branch = int(argv[i])
        elif a == "--preset":
            i += 1
            args = dict(_PRESETS[argv[i]])
        elif a.startswith("--") and a[2:].replace("-", "_") in args:
            i += 1
            args[a[2:].replace("-", "_")] = int(argv[i])
        else:
            raise SystemExit(f"unknown flag {a!r}")
        i += 1
    here = os.path.dirname(os.path.abspath(__file__))
    if mode == "paged":
        result = run_paged(**args)
        with open(os.path.join(here, "BENCH_PAGED.json"), "w") as f:
            json.dump(result, f, indent=2)
            f.write("\n")
    elif mode == "spec":
        result = run_spec(spec_k=spec_k, **args)
        with open(os.path.join(here, "BENCH_SPEC.json"), "w") as f:
            json.dump(result, f, indent=2)
            f.write("\n")
    elif mode == "spec_tree":
        result = run_spec_tree(
            spec_k=spec_k, spec_branch=spec_branch, **args
        )
        with open(os.path.join(here, "BENCH_SPEC_TREE.json"), "w") as f:
            json.dump(result, f, indent=2)
            f.write("\n")
        for arm, frac in result["greedy_streams_match"].items():
            n_match, n_all = frac.split("/")
            if n_match != n_all:
                raise SystemExit(
                    f"tree speculation moved greedy streams: {arm} arm "
                    f"matched {frac}"
                )
        if result["vs_baseline"] < 1.2:
            raise SystemExit(
                f"tree speculation missed the accepted-per-verify gate: "
                f"{result['vs_baseline']}x the equal-budget linear chain "
                f"(floor 1.2x)"
            )
    elif mode == "decode_kernel":
        result = run_decode_kernel(decode_kernel=decode_kernel, **args)
        with open(os.path.join(here, "BENCH_DECODE_KERNEL.json"), "w") as f:
            json.dump(result, f, indent=2)
            f.write("\n")
    elif mode == "chunked":
        result = run_chunked(**args)
        with open(os.path.join(here, "BENCH_CHUNKED.json"), "w") as f:
            json.dump(result, f, indent=2)
            f.write("\n")
        n_match, n_all = result["streams_match"].split("/")
        if n_match != n_all:
            raise SystemExit(
                f"chunked prefill moved greedy streams: "
                f"{result['streams_match']} matched"
            )
        if (
            result["ttft_p95_ratio"] < 1.3
            or result["itl_p95_ratio"] < 1.3
        ):
            raise SystemExit(
                f"chunked prefill missed the latency gates: "
                f"p95 TTFT {result['ttft_p95_ratio']}x, "
                f"p95 ITL {result['itl_p95_ratio']}x (floor 1.3x)"
            )
        if result["throughput_ratio"] < 0.95:
            raise SystemExit(
                f"chunked prefill regressed decode throughput: "
                f"{result['throughput_ratio']}x unchunked (floor 0.95x)"
            )
    elif mode == "prefix":
        result = run_prefix(**args)
        with open(os.path.join(here, "BENCH_PREFIX.json"), "w") as f:
            json.dump(result, f, indent=2)
            f.write("\n")
        if result["vs_baseline"] < 2.0:
            raise SystemExit(
                f"prefix sharing missed the capacity gate: "
                f"{result['vs_baseline']}x concurrent requests at equal "
                f"bytes (floor 2.0x)"
            )
        if result["int8_capacity_ratio"] < 1.8:
            raise SystemExit(
                f"int8 KV missed the capacity gate: "
                f"{result['int8_capacity_ratio']}x over fp32+prefix at "
                f"equal bytes (floor 1.8x)"
            )
        if result["throughput_ratio"] < 0.95:
            raise SystemExit(
                f"int8+prefix regressed decode throughput: "
                f"{result['throughput_ratio']}x fp32 paged (floor 0.95x)"
            )
    elif mode == "pod":
        result = run_pod(**args)
        with open(os.path.join(here, "BENCH_POD.json"), "w") as f:
            json.dump(result, f, indent=2)
            f.write("\n")
        if result["vs_baseline"] < 3.0:
            raise SystemExit(
                f"pod serving missed the capacity gate: "
                f"{result['vs_baseline']}x peak concurrent requests at "
                f"equal per-host pages over {result['hosts']} simulated "
                f"hosts (floor 3.0x)"
            )
    elif mode == "telemetry":
        result = run_telemetry(**args)
        with open(os.path.join(here, "BENCH_TELEMETRY.json"), "w") as f:
            json.dump(result, f, indent=2)
            f.write("\n")
    elif mode == "pressure":
        result = run_pressure(seed=seed, **args)
        with open(os.path.join(here, "BENCH_PRESSURE.json"), "w") as f:
            json.dump(result, f, indent=2)
            f.write("\n")
        if result["value"] < 1.3:
            raise SystemExit(
                f"swap-to-host missed the goodput gate: "
                f"{result['value']}x recompute-only under forced "
                f"pressure (floor 1.3x; sync "
                f"{result['sync']['ratio']}x, async "
                f"{result['async']['ratio']}x)"
            )
    elif mode == "frontdoor":
        result = run_frontdoor(seed=seed, **args)
        with open(os.path.join(here, "BENCH_FRONTDOOR.json"), "w") as f:
            json.dump(result, f, indent=2)
            f.write("\n")
        if result["itl_p99_ratio"] < 1.3:
            raise SystemExit(
                f"disaggregation missed the decode-ITL gate: p99 "
                f"{result['itl_p99_ratio']}x monolithic (floor 1.3x)"
            )
        if result["goodput_ratio"] < 0.95:
            raise SystemExit(
                f"disaggregation regressed goodput: "
                f"{result['goodput_ratio']}x monolithic (floor 0.95x)"
            )
    elif mode == "tenancy":
        result = run_tenancy(seed=seed, **args)
        with open(os.path.join(here, "BENCH_TENANCY.json"), "w") as f:
            json.dump(result, f, indent=2)
            f.write("\n")
        # the hard gates (attainment, starvation bound, zero lost,
        # stream identity) already raised inside run_tenancy on miss
    elif mode == "multistep":
        result = run_multistep(**args)
        with open(os.path.join(here, "BENCH_MULTISTEP.json"), "w") as f:
            json.dump(result, f, indent=2)
            f.write("\n")
        n_match, n_all = result["streams_match"].split("/")
        if n_match != n_all:
            raise SystemExit(
                f"multi-step decode moved greedy streams: "
                f"{result['streams_match']} matched"
            )
        if result["vs_baseline"] < 4.0:
            raise SystemExit(
                f"multi-step decode missed the host-sync gate: "
                f"{result['vs_baseline']}x steps-per-host-sync over "
                f"step-at-a-time (floor 4.0x)"
            )
    elif mode == "chaos":
        result = run_chaos(seed=seed, serve_async=serve_async, **args)
        name = "BENCH_CHAOS_ASYNC.json" if serve_async else "BENCH_CHAOS.json"
        with open(os.path.join(here, name), "w") as f:
            json.dump(result, f, indent=2)
            f.write("\n")
    elif mode == "recovery":
        result = run_recovery(seed=seed, **args)
        with open(os.path.join(here, "BENCH_RECOVERY.json"), "w") as f:
            json.dump(result, f, indent=2)
            f.write("\n")
        # the crash-fired / zero-lost / stream-identity gates already
        # raised inside run_recovery on miss
    elif serve_async:
        result = run_async(**args)
        with open(os.path.join(here, "BENCH_ASYNC.json"), "w") as f:
            json.dump(result, f, indent=2)
            f.write("\n")
        if result["vs_baseline"] < 0.95 or result["overlap_fraction"] <= 0:
            raise SystemExit(
                f"async engine regressed: {result['vs_baseline']}x sync, "
                f"overlap {result['overlap_fraction']}"
            )
    else:
        result = run(**args)
    print(json.dumps(result))


if __name__ == "__main__":
    main()
