"""Keras-frontend CNN on CIFAR-10 (reference: examples/python/keras/
func_cifar10_cnn.py and friends — 28 keras scripts in the reference zoo).

Uses the keras dataset loaders (synthetic fallback when no cached copy
exists) and the Sequential API over the FFModel builder.

    python examples/keras_cnn_cifar10.py -b 64 -i 4 -e 1
"""

from __future__ import annotations

import os
import sys

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from flexflow_tpu import FFConfig  # noqa: E402
from flexflow_tpu.frontends import keras_api as keras  # noqa: E402
from flexflow_tpu.frontends.keras_datasets import load_cifar10  # noqa: E402


def build(cfg: FFConfig):
    return keras.Sequential(
        [
            keras.Input(shape=(32, 32, 3)),
            keras.Conv2D(32, (3, 3), padding="same", activation="relu"),
            keras.Conv2D(32, (3, 3), padding="same", activation="relu"),
            keras.MaxPooling2D((2, 2), strides=(2, 2)),
            keras.Conv2D(64, (3, 3), padding="same", activation="relu"),
            keras.Conv2D(64, (3, 3), padding="same", activation="relu"),
            keras.MaxPooling2D((2, 2), strides=(2, 2)),
            keras.Flatten(),
            keras.Dense(512, activation="relu"),
            keras.Dense(10),
        ],
        config=cfg,
    )


def main():
    cfg = FFConfig.parse_args()
    if cfg.dataset_path:  # -d/--dataset (reference: dataset_path)
        os.environ["FF_DATASETS_DIR"] = cfg.dataset_path
    n = cfg.batch_size * (cfg.iterations or 4)
    (x_train, y_train), _ = load_cifar10(n_train=n, n_test=max(cfg.batch_size, 1))
    x = (x_train.astype(np.float32) / 255.0)[:n]
    y = y_train.reshape(-1)[:n].astype(np.int32)

    model = build(cfg)
    model.compile(
        optimizer=keras.SGD(cfg.learning_rate, momentum=0.9),
        loss="sparse_categorical_crossentropy",
        metrics=["accuracy"],
    )
    model.fit(x, y, epochs=cfg.epochs)


if __name__ == "__main__":
    main()
