"""Transformer benchmark workload.

Mirror of the reference example (reference: examples/cpp/Transformer/
transformer.cc:79-85 config — 12 layers, hidden 1024, 16 heads, seq 512;
encoder layer :33-45 = MHA then two biasless dense layers; final dense(1),
SGD lr 0.01, MSE loss, THROUGHPUT print :209).
"""

from __future__ import annotations

import os
import sys

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from flexflow_tpu import (  # noqa: E402
    ActiMode,
    FFConfig,
    FFModel,
    LossType,
    SGDOptimizer,
)


def build_transformer(
    config: FFConfig = None,
    batch_size: int = 8,
    seq_len: int = 512,
    hidden: int = 1024,
    num_heads: int = 16,
    num_layers: int = 12,
    compile_now: bool = True,
    devices=None,
):
    cfg = config or FFConfig(batch_size=batch_size, learning_rate=0.01)
    cfg.batch_size = batch_size
    model = FFModel(cfg)
    x = model.create_tensor([batch_size, seq_len, hidden], name="x")
    t = x
    for _ in range(num_layers):
        t = model.multihead_attention(t, t, t, hidden, num_heads)
        t = model.dense(t, hidden, activation=ActiMode.RELU, use_bias=False)
        t = model.dense(t, hidden, use_bias=False)
    t = model.dense(t, 1, use_bias=False)
    if compile_now:
        model.compile(
            optimizer=SGDOptimizer(lr=0.01),
            loss_type=LossType.MEAN_SQUARED_ERROR_AVG_REDUCE,
            metrics=[],
            devices=devices,
        )
    return model, t


def synthetic_batch(batch_size=8, seq_len=512, hidden=1024, seed=0):
    rng = np.random.RandomState(seed)
    return {
        "x": rng.randn(batch_size, seq_len, hidden).astype(np.float32),
        "label": rng.randn(batch_size, seq_len, 1).astype(np.float32),
    }


def main():
    cfg = FFConfig.parse_args()
    model, _ = build_transformer(cfg, batch_size=cfg.batch_size)
    num_samples = cfg.batch_size * (cfg.iterations or 32)
    batch = synthetic_batch(num_samples, 512, 1024)
    model.fit(batch["x"], batch["label"], epochs=cfg.epochs)


if __name__ == "__main__":
    main()
