"""End-to-end workflow tour: build -> search -> export strategy ->
re-import -> train -> checkpoint -> resume (the complete user journey the
reference spreads over --export/--import (strategy.cc:100-197), fit()
(flexflow_cffi.py:1916), and external torch-state-dict scripts; the
checkpoint/resume leg is beyond-reference, SURVEY §5).

    python examples/full_workflow.py [-b 64] [--budget 10]
"""

from __future__ import annotations

import os
import sys
import tempfile

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np  # noqa: E402

from flexflow_tpu import (  # noqa: E402
    ActiMode,
    FFConfig,
    FFModel,
    LossType,
    MetricsType,
    SGDOptimizer,
)


def build(cfg: FFConfig) -> FFModel:
    ff = FFModel(cfg)
    x = ff.create_tensor([cfg.batch_size, 64], name="x")
    t = ff.dense(x, 256, activation=ActiMode.RELU)
    t = ff.dense(t, 256, activation=ActiMode.RELU)
    ff.dense(t, 8)
    return ff


def main():
    cfg = FFConfig.parse_args()
    workdir = tempfile.mkdtemp(prefix="ff_workflow_")
    strategy_path = os.path.join(workdir, "strategy.json")
    ckpt_dir = os.path.join(workdir, "ckpt")

    # 1) search a strategy and export it
    cfg.search_budget = max(cfg.search_budget, 10)
    cfg.export_strategy_file = strategy_path
    model = build(cfg)
    model.compile(
        optimizer=SGDOptimizer(lr=0.05),
        loss_type=LossType.SPARSE_CATEGORICAL_CROSSENTROPY,
        metrics=[MetricsType.ACCURACY],
    )
    print(f"searched strategy: {model.strategy.name}")
    print(f"exported to {strategy_path}")

    rng = np.random.RandomState(0)
    n = cfg.batch_size * 4
    x = rng.randn(n, 64).astype(np.float32)
    y = rng.randint(0, 8, n).astype(np.int32)

    # 2) fresh process analog: import the exported strategy, train with
    # periodic checkpoints
    cfg2 = FFConfig(batch_size=cfg.batch_size)
    cfg2.import_strategy_file = strategy_path
    model2 = build(cfg2)
    model2.compile(
        optimizer=SGDOptimizer(lr=0.05),
        loss_type=LossType.SPARSE_CATEGORICAL_CROSSENTROPY,
        metrics=[MetricsType.ACCURACY],
    )
    print(f"imported strategy: {model2.strategy.name}")
    model2.fit(x, y, epochs=2, checkpoint_dir=ckpt_dir, checkpoint_every=1)

    # 3) resume from the checkpoint and keep training
    model3 = build(FFConfig(batch_size=cfg.batch_size))
    model3.compile(
        optimizer=SGDOptimizer(lr=0.05),
        loss_type=LossType.SPARSE_CATEGORICAL_CROSSENTROPY,
        metrics=[MetricsType.ACCURACY],
    )
    step = model3.restore_checkpoint(ckpt_dir)
    print(f"resumed from step {step}")
    hist = model3.fit(x, y, epochs=1)
    print(f"final loss_sum {hist[-1]['loss_sum']:.4f}")
    print("WORKFLOW OK")


if __name__ == "__main__":
    main()
