"""BERT-base proxy blocks (reference:
examples/python/native/bert_proxy_native.py; OSDI22 AE bert.sh runs this
shape with --budget 30 on 4 devices).

    python examples/bert_proxy.py -b 8 -e 1 --budget 30
"""

import numpy as np

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from examples.common import run_training

from flexflow_tpu import (  # noqa: E402
    FFConfig,
    FFModel,
    LossType,
    MetricsType,
    SGDOptimizer,
)
from flexflow_tpu.models import build_bert_proxy  # noqa: E402


def main():
    cfg = FFConfig.parse_args()
    seq, hidden, heads, layers = 512, 768, 12, 12
    ff = FFModel(cfg)
    x = ff.create_tensor([cfg.batch_size, seq, hidden], name="hidden_states")
    t = build_bert_proxy(ff, x, hidden=hidden, num_heads=heads,
                         num_layers=layers)
    ff.dense(t, 1, use_bias=False)  # regression head for the proxy loss
    ff.compile(
        optimizer=SGDOptimizer(lr=0.0001),
        loss_type=LossType.MEAN_SQUARED_ERROR_AVG_REDUCE,
        metrics=[MetricsType.MEAN_SQUARED_ERROR],
    )
    n = cfg.batch_size * (cfg.iterations or 4)
    rng = np.random.RandomState(0)
    data = {"hidden_states": rng.randn(n, seq, hidden).astype(np.float32)}
    y = rng.randn(n, seq, 1).astype(np.float32)
    run_training(ff, data, y, cfg)


if __name__ == "__main__":
    main()
