/* C API demo 2: a small conv net built entirely from C — conv2d with
 * initializers, pool, batch-norm, concat, Adam optimizer handle, and a
 * post-training parameter round-trip (get/set weights).
 * (reference surface: python/flexflow_c.h per-layer constructors,
 * flexflow_parameter_get/set_weights_float) */

#include <math.h>
#include <stdio.h>
#include <stdlib.h>

#include "flexflow_c.h"

#define CHECK(x)                                         \
  do {                                                   \
    if (!(x)) {                                          \
      fprintf(stderr, "FAILED: %s (line %d)\n", #x, __LINE__); \
      return 1;                                          \
    }                                                    \
  } while (0)

int main(void) {
  CHECK(flexflow_init(0, NULL) == 0);

  char *argv[] = {"-b", "8", "-e", "1"};
  flexflow_config_t cfg = flexflow_config_create(4, argv);
  CHECK(cfg != NULL);
  CHECK(flexflow_config_get_batch_size(cfg) == 8);
  CHECK(flexflow_config_get_epochs(cfg) == 1);

  flexflow_model_t model = flexflow_model_create(cfg);
  CHECK(model != NULL);

  int dims[4] = {8, 16, 16, 3}; /* NHWC */
  flexflow_tensor_t x = flexflow_tensor_create(model, 4, dims, "image");
  CHECK(x != NULL);
  CHECK(flexflow_tensor_get_num_dims(x) == 4);
  int got[4];
  CHECK(flexflow_tensor_get_dims(x, got, 4) == 4);
  CHECK(got[3] == 3);

  flexflow_initializer_t glorot = flexflow_glorot_uniform_initializer_create(7);
  flexflow_initializer_t zero = flexflow_zero_initializer_create();
  CHECK(glorot != NULL && zero != NULL);

  /* two parallel conv branches, concatenated (exercises concat) */
  flexflow_tensor_t a = flexflow_model_add_conv2d_ex(
      model, x, 8, 3, 3, 1, 1, 1, 1, /*relu*/ 1, /*groups*/ 1,
      /*use_bias*/ 1, glorot, zero);
  flexflow_tensor_t b = flexflow_model_add_conv2d(model, x, 8, 5, 5, 1, 1, 2,
                                                  2, /*relu*/ 1);
  CHECK(a != NULL && b != NULL);
  flexflow_tensor_t branches[2] = {a, b};
  flexflow_tensor_t t = flexflow_model_add_concat(model, 2, branches, 3);
  CHECK(t != NULL);
  t = flexflow_model_add_batch_norm(model, t, 1);
  t = flexflow_model_add_pool2d(model, t, 2, 2, 2, 2, 0, 0, 0);
  CHECK(t != NULL);
  t = flexflow_model_add_flat(model, t);
  /* scalar ops (incl. the reference's "truediv" spelling) */
  t = flexflow_model_add_scalar_multiply(model, t, 2.0f);
  t = flexflow_model_add_scalar_truediv(model, t, 2.0f);
  CHECK(t != NULL);
  t = flexflow_model_add_dense_ex(model, t, 32, /*relu*/ 1, 1, glorot, zero);
  flexflow_tensor_t logits = flexflow_model_add_dense(model, t, 4, 0, 1);
  CHECK(logits != NULL);

  flexflow_adam_optimizer_t adam =
      flexflow_adam_optimizer_create(model, 0.001, 0.9, 0.999, 0.0, 1e-8);
  CHECK(adam != NULL);
  flexflow_adam_optimizer_set_lr(adam, 0.002);
  CHECK(flexflow_model_set_adam_optimizer(model, adam) == 0);

  CHECK(flexflow_model_compile(model, "sparse_categorical_crossentropy",
                               "accuracy", 0.001) == 0);

  /* introspection: the last layer is the logits dense; round-trip its
   * kernel through host buffers */
  flexflow_op_t last = flexflow_model_get_last_layer(model);
  CHECK(last != NULL);
  CHECK(flexflow_op_get_num_parameters(last) == 2); /* kernel + bias */
  flexflow_parameter_t kernel = flexflow_op_get_parameter_by_id(last, 0);
  CHECK(kernel != NULL);
  int64_t n = flexflow_parameter_get_num_elements(kernel);
  CHECK(n == 32 * 4);
  float *w = (float *)malloc(n * sizeof(float));
  CHECK(flexflow_parameter_get_weights_float(kernel, w, n) == 0);
  for (int64_t i = 0; i < n; ++i) w[i] = 0.25f;
  CHECK(flexflow_parameter_set_weights_float(kernel, w, n) == 0);
  CHECK(flexflow_parameter_get_weights_float(kernel, w, n) == 0);
  CHECK(fabsf(w[0] - 0.25f) < 1e-6f);
  free(w);

  /* train one epoch through fit */
  int num = 32;
  float *X = (float *)malloc((size_t)num * 16 * 16 * 3 * sizeof(float));
  int *Y = (int *)malloc((size_t)num * sizeof(int));
  for (int i = 0; i < num * 16 * 16 * 3; ++i)
    X[i] = (float)((i * 2654435761u) % 1000) / 1000.0f - 0.5f;
  for (int i = 0; i < num; ++i) Y[i] = i % 4;
  int64_t xs[4] = {num, 16, 16, 3};
  int64_t ys[1] = {num};
  double loss = flexflow_model_fit(model, X, xs, 4, Y, ys, 1, /*y_is_int*/ 1,
                                   /*epochs*/ 1);
  CHECK(!isnan(loss));
  printf("capi_cnn ok (loss %.4f)\n", loss);

  free(X);
  free(Y);
  flexflow_handle_destroy(kernel);
  flexflow_handle_destroy(last);
  flexflow_adam_optimizer_destroy(adam);
  flexflow_initializer_destroy(glorot);
  flexflow_initializer_destroy(zero);
  flexflow_model_destroy(model);
  flexflow_config_destroy(cfg);
  flexflow_finalize();
  return 0;
}
