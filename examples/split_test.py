"""split_test: exercises the Split op inside a trained graph
(reference: examples/cpp/split_test/split_test.cc and split_test_2 —
a dense stack whose hidden tensor is split and re-concatenated).

    python examples/split_test.py -b 16 -e 1
"""

import numpy as np

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from examples.common import run_training

from flexflow_tpu import (  # noqa: E402
    ActiMode,
    FFConfig,
    FFModel,
    LossType,
    MetricsType,
    SGDOptimizer,
)


def main():
    cfg = FFConfig.parse_args()
    ff = FFModel(cfg)
    x = ff.create_tensor([cfg.batch_size, 64], name="x")
    t = ff.dense(x, 64, activation=ActiMode.RELU)
    a, b = ff.split(t, 2, axis=1)
    a = ff.dense(a, 32, activation=ActiMode.RELU)
    b = ff.dense(b, 32, activation=ActiMode.RELU)
    t = ff.concat([a, b], axis=1)
    t = ff.dense(t, 10)
    ff.softmax(t)
    ff.compile(
        optimizer=SGDOptimizer(lr=0.01),
        loss_type=LossType.SPARSE_CATEGORICAL_CROSSENTROPY,
        metrics=[MetricsType.ACCURACY],
    )
    n = cfg.batch_size * (cfg.iterations or 8)
    rng = np.random.RandomState(0)
    X = rng.randn(n, 64).astype(np.float32)
    y = rng.randint(0, 10, size=n).astype(np.int32)
    run_training(ff, {"x": X}, y, cfg)


if __name__ == "__main__":
    main()
