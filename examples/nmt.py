"""Seq2seq NMT workload — the reference's legacy standalone RNN/LSTM
mini-framework as an example (reference: nmt/ — 3,980 LoC with its own
RnnModel, rnn_mapper, and CUDA kernels nmt/lstm.cu, embed.cu, linear.cu;
SURVEY §1 treats it as an example workload, not core).

TPU re-design: the LSTM recurrence is a `lax.scan` (XLA unrolls it onto
the MXU), embedding/projection are plain jnp ops, the whole train step is
one jitted function, and the update reuses the framework's SGDOptimizer.

    python examples/nmt.py -b 32 -i 4 -e 1
"""

from __future__ import annotations

import os
import sys
import time

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax  # noqa: E402
import jax.numpy as jnp  # noqa: E402

from flexflow_tpu import FFConfig, SGDOptimizer  # noqa: E402

VOCAB = 256
EMBED = 64
HIDDEN = 128
SEQ = 16


def init_params(key, vocab=VOCAB, embed=EMBED, hidden=HIDDEN):
    """Encoder LSTM + decoder LSTM + shared embedding + output projection
    (reference: nmt/rnn.h's LSTM/Embed/Linear node zoo)."""
    ks = jax.random.split(key, 8)
    g = jax.nn.initializers.glorot_uniform()

    def lstm(k):
        k1, k2 = jax.random.split(k)
        return {
            "wx": g(k1, (embed, 4 * hidden)),
            "wh": g(k2, (hidden, 4 * hidden)),
            "b": jnp.zeros((4 * hidden,)),
        }

    return {
        "embed_src": g(ks[0], (vocab, embed)),
        "embed_dst": g(ks[3], (vocab, embed)),
        "enc": lstm(ks[1]),
        "dec": lstm(ks[2]),
        "proj_w": g(ks[4], (hidden, vocab)),
        "proj_b": jnp.zeros((vocab,)),
    }


def lstm_scan(cell, xs, h0, c0):
    """xs: [seq, batch, embed] → hs: [seq, batch, hidden]
    (reference kernel: nmt/lstm.cu — cuDNN-style fused gates)."""

    def step(carry, x):
        h, c = carry
        gates = x @ cell["wx"] + h @ cell["wh"] + cell["b"]
        i, f, gq, o = jnp.split(gates, 4, axis=-1)
        c = jax.nn.sigmoid(f) * c + jax.nn.sigmoid(i) * jnp.tanh(gq)
        h = jax.nn.sigmoid(o) * jnp.tanh(c)
        return (h, c), h

    (h, c), hs = jax.lax.scan(step, (h0, c0), xs)
    return hs, h, c


def forward(params, src, dst_in):
    """src, dst_in: [batch, seq] int32 → logits [batch, seq, vocab]."""
    batch = src.shape[0]
    h0 = jnp.zeros((batch, HIDDEN))
    c0 = jnp.zeros((batch, HIDDEN))
    x_src = params["embed_src"][src].transpose(1, 0, 2)  # [seq, b, e]
    _, h, c = lstm_scan(params["enc"], x_src, h0, c0)
    x_dst = params["embed_dst"][dst_in].transpose(1, 0, 2)
    hs, _, _ = lstm_scan(params["dec"], x_dst, h, c)  # teacher forcing
    logits = hs.transpose(1, 0, 2) @ params["proj_w"] + params["proj_b"]
    return logits


def loss_fn(params, batch):
    logits = forward(params, batch["src"], batch["dst_in"])
    logp = jax.nn.log_softmax(logits, axis=-1)
    nll = -jnp.take_along_axis(
        logp, batch["dst_out"][..., None], axis=-1
    ).squeeze(-1)
    return nll.mean()


def synthetic_batch(rng, batch, seq=SEQ):
    """Copy task: target = source reversed (a learnable seq2seq toy)."""
    src = rng.randint(1, VOCAB, size=(batch, seq)).astype(np.int32)
    tgt = src[:, ::-1].copy()
    dst_in = np.concatenate([np.zeros((batch, 1), np.int32), tgt[:, :-1]], 1)
    return {
        "src": src,
        "dst_in": dst_in,
        "dst_out": tgt,
    }


def main():
    cfg = FFConfig.parse_args()
    batch = cfg.batch_size
    iters = cfg.iterations or 8
    opt = SGDOptimizer(lr=cfg.learning_rate)

    params = init_params(jax.random.PRNGKey(cfg.seed))
    opt_state = opt.init_state(params)

    @jax.jit
    def train_step(params, opt_state, batch):
        loss, grads = jax.value_and_grad(loss_fn)(params, batch)
        params, opt_state = opt.update(params, grads, opt_state)
        return params, opt_state, loss

    rng = np.random.RandomState(cfg.seed)
    loss = None
    t0 = time.perf_counter()
    for epoch in range(cfg.epochs):
        for _ in range(iters):
            b = {k: jnp.asarray(v) for k, v in synthetic_batch(rng, batch).items()}
            params, opt_state, loss = train_step(params, opt_state, b)
    loss = float(np.asarray(loss))
    elapsed = time.perf_counter() - t0
    n = batch * iters * cfg.epochs
    # reference examples print exactly this (e.g. transformer.cc:209)
    print(f"ELAPSED TIME = {elapsed:.4f}s, THROUGHPUT = {n / elapsed:.2f} samples/s")
    print(f"final loss {loss:.4f}")
    assert np.isfinite(loss)


if __name__ == "__main__":
    main()
