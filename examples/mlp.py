"""MLP_Unify twin-tower MLP (reference: examples/cpp/MLP_Unify/mlp.cc).

    python examples/mlp.py -b 64 -e 1 [--budget N]
"""

import numpy as np

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from examples.common import run_training

from flexflow_tpu import (  # noqa: E402
    FFConfig,
    FFModel,
    LossType,
    MetricsType,
    SGDOptimizer,
)
from flexflow_tpu.models import build_mlp_unify  # noqa: E402


def main():
    cfg = FFConfig.parse_args()
    ff = FFModel(cfg)
    x1 = ff.create_tensor([cfg.batch_size, 1024], name="input1")
    x2 = ff.create_tensor([cfg.batch_size, 1024], name="input2")
    build_mlp_unify(ff, x1, x2)
    ff.compile(
        optimizer=SGDOptimizer(lr=0.001),
        loss_type=LossType.SPARSE_CATEGORICAL_CROSSENTROPY,
        metrics=[MetricsType.ACCURACY, MetricsType.SPARSE_CATEGORICAL_CROSSENTROPY],
    )
    n = cfg.batch_size * (cfg.iterations or 8)
    rng = np.random.RandomState(0)
    data = {
        "input1": rng.randn(n, 1024).astype(np.float32),
        "input2": rng.randn(n, 1024).astype(np.float32),
    }
    y = rng.randint(0, 8192, size=n).astype(np.int32)
    run_training(ff, data, y, cfg)


if __name__ == "__main__":
    main()
