"""Serving demo: decoder LM + continuous-batching generate().

Builds a small GPT-style decoder (models.build_decoder_lm), compiles it,
and serves a mixed-length prompt stream through the continuous-batching
scheduler, printing generations and the scheduler's occupancy — run with
`--serve-scheduler static` to watch the occupancy (and tokens/s) drop on
the same stream. Serving flags ride FFConfig: `--max-seqs 4
--max-seq-len 128 --eos-token 0`. Telemetry flags ride along too — try
`--trace /tmp/serve_trace.json --metrics-out /tmp/serve_metrics.prom
--slo-ttft-ms 200` and load the trace at https://ui.perfetto.dev
(docs/observability.md).
"""

from __future__ import annotations

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from flexflow_tpu import (  # noqa: E402
    DataType,
    FFConfig,
    FFModel,
    LossType,
    SGDOptimizer,
)
from flexflow_tpu.models import build_decoder_lm  # noqa: E402
from flexflow_tpu.serving import Request, ServeConfig, build_scheduler  # noqa: E402

VOCAB = 512


def build_lm(cfg: FFConfig, vocab: int = VOCAB, hidden: int = 128,
             heads: int = 8, layers: int = 4):
    model = FFModel(cfg)
    tokens = model.create_tensor(
        [cfg.batch_size, cfg.serve_max_seq_len],
        dtype=DataType.INT32,
        name="tokens",
    )
    build_decoder_lm(
        model, tokens, vocab_size=vocab, hidden=hidden, num_heads=heads,
        num_layers=layers, ff_dim=4 * hidden,
    )
    model.compile(
        optimizer=SGDOptimizer(lr=0.01),
        loss_type=LossType.SPARSE_CATEGORICAL_CROSSENTROPY,
        metrics=[],
    )
    return model


def main():
    cfg = FFConfig.parse_args()
    model = build_lm(cfg)
    serve = ServeConfig.from_config(cfg)
    sched, _, cache = build_scheduler(model, serve)
    if cache.paged:
        print(
            f"paged KV cache: {cache.spec.num_pages} pages of "
            f"{cache.spec.page_size} tokens "
            f"(try --kv-page-size / --kv-pages / --kv-layout slot)"
        )
    requests = [
        Request(
            rid=i,
            prompt=[(i * 13 + j) % VOCAB for j in range(1 + i % 7)],
            max_new_tokens=4 if i % 2 == 0 else 24,
            eos_token=serve.eos_token,
        )
        for i in range(3 * serve.max_seqs)
    ]
    done = sched.run(requests)
    for r in sorted(done, key=lambda r: r.rid):
        print(f"req {r.rid}: prompt {r.prompt} -> {r.generated}")
    s = sched.stats
    print(
        f"[{serve.scheduler}] {s.tokens_generated} tokens, "
        f"{s.decode_steps} decode steps, occupancy {s.occupancy:.2f}, "
        f"peak in-flight {s.peak_in_flight}, {s.tokens_per_s:.0f} tokens/s"
    )
    if sched.telemetry is not None:
        slo = sched.telemetry.slo.snapshot()
        print(
            f"telemetry: p95 TTFT {slo['ttft_ms']['p95']:.1f}ms, "
            f"p95 ITL {slo['itl_ms']['p95']:.2f}ms, "
            f"violations {slo['violations']}"
            + (f", trace -> {serve.trace}" if serve.trace else "")
            + (f", metrics -> {serve.metrics_out}" if serve.metrics_out else "")
        )


if __name__ == "__main__":
    main()
