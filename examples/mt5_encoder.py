"""mT5-small encoder (BASELINE config #4; reference: align/mt5_encoder —
embedding + layernorm + attention under parallel rewrites).

    python examples/mt5_encoder.py -b 8 -e 1 [--budget N]
"""

import numpy as np

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from examples.common import run_training

from flexflow_tpu import (  # noqa: E402
    DataType,
    FFConfig,
    FFModel,
    LossType,
    MetricsType,
    AdamOptimizer,
)
from flexflow_tpu.models import build_mt5_encoder  # noqa: E402


def main():
    cfg = FFConfig.parse_args()
    vocab, seq, hidden, heads, layers = 32128, 128, 512, 8, 8
    ff = FFModel(cfg)
    ids = ff.create_tensor([cfg.batch_size, seq], dtype=DataType.INT32,
                           name="input_ids")
    t = build_mt5_encoder(ff, ids, vocab_size=vocab, hidden=hidden,
                          num_heads=heads, num_layers=layers)
    ff.dense(t, 1, use_bias=False)
    ff.compile(
        optimizer=AdamOptimizer(alpha=0.0001),
        loss_type=LossType.MEAN_SQUARED_ERROR_AVG_REDUCE,
        metrics=[MetricsType.MEAN_SQUARED_ERROR],
    )
    n = cfg.batch_size * (cfg.iterations or 4)
    rng = np.random.RandomState(0)
    data = {"input_ids": rng.randint(0, vocab, size=(n, seq)).astype(np.int32)}
    y = rng.randn(n, seq, 1).astype(np.float32)
    run_training(ff, data, y, cfg)


if __name__ == "__main__":
    main()
