"""ResNeXt-50 (32x4d) on synthetic data
(reference: examples/cpp/resnext50/resnext.cc; OSDI22 AE resnext-50.sh).

    python examples/resnext.py -b 32 -e 1 [--budget N]
"""

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from examples.common import run_training, synthetic_images

from flexflow_tpu import (  # noqa: E402
    FFConfig,
    FFModel,
    LossType,
    MetricsType,
    SGDOptimizer,
)
from flexflow_tpu.models import build_resnext50  # noqa: E402


def main():
    cfg = FFConfig.parse_args()
    ff = FFModel(cfg)
    x = ff.create_tensor([cfg.batch_size, 224, 224, 3], name="image")
    build_resnext50(ff, x, num_classes=10)
    ff.compile(
        optimizer=SGDOptimizer(lr=0.001),
        loss_type=LossType.SPARSE_CATEGORICAL_CROSSENTROPY,
        metrics=[MetricsType.ACCURACY],
    )
    n = cfg.batch_size * (cfg.iterations or 4)
    X, y = synthetic_images(n, 224, 224)
    run_training(ff, {"image": X}, y, cfg)


if __name__ == "__main__":
    main()
