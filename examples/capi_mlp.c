/* C-API demo: build and train an MLP from C (reference: the C++ examples
 * linking the FlexFlow C++ API, e.g. examples/cpp/MLP_Unify/mlp.cc).
 *
 * Build (after `make -C native capi`):
 *   gcc examples/capi_mlp.c -Inative/include -Lnative/build -lflexflow_c \
 *       -Wl,-rpath,native/build -o /tmp/capi_mlp
 *   FF_CAPI_PLATFORM=cpu /tmp/capi_mlp
 */

#include <math.h>
#include <stdint.h>
#include <stdio.h>
#include <stdlib.h>

#include "flexflow_c.h"

int main(int argc, char **argv) {
  if (flexflow_init(argc, argv) != 0) return 1;

  char *cfg_argv[] = {(char *)"-b", (char *)"16"};
  flexflow_config_t cfg = flexflow_config_create(2, cfg_argv);
  flexflow_model_t model = flexflow_model_create(cfg);

  int dims[2] = {16, 32};
  flexflow_tensor_t x = flexflow_tensor_create(model, 2, dims, "x");
  flexflow_tensor_t t =
      flexflow_model_add_dense(model, x, 32, /*relu=*/1, /*bias=*/1);
  t = flexflow_model_add_dense(model, t, 4, /*none=*/0, /*bias=*/1);
  if (t == NULL) return 1;

  if (flexflow_model_compile(model, "sparse_categorical_crossentropy",
                             "accuracy", 0.1) != 0)
    return 1;

  /* synthetic learnable data: label = argmax of 4 fixed feature sums */
  enum { N = 64, D = 32, C = 4 };
  static float xs[N * D];
  static int32_t ys[N];
  unsigned seed = 7;
  for (int i = 0; i < N; ++i) {
    float best = -1e9f;
    int cls = 0;
    for (int j = 0; j < D; ++j) {
      seed = seed * 1103515245u + 12345u;
      xs[i * D + j] = ((float)(seed >> 16 & 0x7fff) / 16384.0f) - 1.0f;
    }
    for (int c = 0; c < C; ++c) {
      float s = 0.f;
      for (int j = c; j < D; j += C) s += xs[i * D + j];
      if (s > best) {
        best = s;
        cls = c;
      }
    }
    ys[i] = cls;
  }
  int64_t x_shape[2] = {N, D};
  int64_t y_shape[1] = {N};
  double loss = flexflow_model_fit(model, xs, x_shape, 2, ys, y_shape, 1,
                                   /*y_is_int=*/1, /*epochs=*/4);
  if (isnan(loss)) return 1;
  printf("final loss %.4f\n", loss);

  flexflow_handle_destroy(t);
  flexflow_handle_destroy(x);
  flexflow_handle_destroy(model);
  flexflow_handle_destroy(cfg);
  flexflow_finalize();
  printf("capi_mlp ok\n");
  return 0;
}
