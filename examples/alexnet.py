"""AlexNet on CIFAR-10-sized synthetic data (BASELINE config #1;
reference: bootcamp_demo/ff_alexnet_cifar10.py + examples/cpp/AlexNet).

    python examples/alexnet.py -b 64 -e 1 [--budget N]
"""

import numpy as np

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from examples.common import run_training, synthetic_images

from flexflow_tpu import (  # noqa: E402
    FFConfig,
    FFModel,
    LossType,
    MetricsType,
    SGDOptimizer,
)
from flexflow_tpu.models import build_alexnet  # noqa: E402


def main():
    cfg = FFConfig.parse_args()
    ff = FFModel(cfg)
    # CIFAR-10 images upscaled to the reference's 229x229 input
    # (alexnet.cc:58); NHWC layout.
    x = ff.create_tensor([cfg.batch_size, 229, 229, 3], name="image")
    build_alexnet(ff, x, num_classes=10)
    ff.compile(
        optimizer=SGDOptimizer(lr=0.001),
        loss_type=LossType.SPARSE_CATEGORICAL_CROSSENTROPY,
        metrics=[MetricsType.ACCURACY, MetricsType.SPARSE_CATEGORICAL_CROSSENTROPY],
    )
    n = cfg.batch_size * (cfg.iterations or 8)
    X, y = synthetic_images(n, 229, 229)
    run_training(ff, {"image": X}, y, cfg)


if __name__ == "__main__":
    main()
