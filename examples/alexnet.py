"""AlexNet on CIFAR-10-sized synthetic data (BASELINE config #1;
reference: bootcamp_demo/ff_alexnet_cifar10.py + examples/cpp/AlexNet).

    python examples/alexnet.py -b 64 -e 1 [--budget N]
"""

import numpy as np

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from examples.common import run_training

from flexflow_tpu import (  # noqa: E402
    FFConfig,
    FFModel,
    LossType,
    MetricsType,
    SGDOptimizer,
)
from flexflow_tpu.models import build_alexnet  # noqa: E402


def main():
    cfg = FFConfig.parse_args()
    if cfg.dataset_path:  # -d/--dataset (reference: dataset_path)
        os.environ["FF_DATASETS_DIR"] = cfg.dataset_path
    ff = FFModel(cfg)
    # CIFAR-10 images upscaled to the reference's 229x229 input
    # (alexnet.cc:58); NHWC layout.
    x = ff.create_tensor([cfg.batch_size, 229, 229, 3], name="image")
    build_alexnet(ff, x, num_classes=10)
    ff.compile(
        optimizer=SGDOptimizer(lr=0.001),
        loss_type=LossType.SPARSE_CATEGORICAL_CROSSENTROPY,
        metrics=[MetricsType.ACCURACY, MetricsType.SPARSE_CATEGORICAL_CROSSENTROPY],
    )
    n = cfg.batch_size * (cfg.iterations or 8)
    # CIFAR-10 through the keras loaders (real data when cached, synthetic
    # fallback otherwise — bootcamp_demo/ff_alexnet_cifar10.py parity),
    # nearest-neighbor upscaled 32→229 like the reference demo's resize
    from flexflow_tpu.frontends.keras_datasets import load_cifar10

    (x_tr, y_tr), _ = load_cifar10(n_train=n, n_test=1)
    idx = np.linspace(0, 31, 229).astype(np.int32)
    X = (x_tr[:n].astype(np.float32) / 255.0)[:, idx][:, :, idx]
    y = y_tr.reshape(-1)[:n].astype(np.int32)
    run_training(ff, {"image": X}, y, cfg)


if __name__ == "__main__":
    main()
