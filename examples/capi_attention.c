/* C API demo 3: a transformer block from C — multihead attention, layer
 * norm, residual adds — trained with the REFERENCE training-loop verbs
 * (dataloader next_batch; forward; zero_gradients; backward; update) and
 * scored with the metrics verbs.
 * (reference: flexflow_cffi.py fit loop + flexflow_single_dataloader_*) */

#include <math.h>
#include <stdio.h>
#include <stdlib.h>

#include "flexflow_c.h"

#define CHECK(x)                                         \
  do {                                                   \
    if (!(x)) {                                          \
      fprintf(stderr, "FAILED: %s (line %d)\n", #x, __LINE__); \
      return 1;                                          \
    }                                                    \
  } while (0)

enum { B = 8, S = 16, H = 32, CLASSES = 4, NUM = 32 };

int main(void) {
  CHECK(flexflow_init(0, NULL) == 0);

  char *argv[] = {"-b", "8"};
  flexflow_config_t cfg = flexflow_config_create(2, argv);
  flexflow_model_t model = flexflow_model_create(cfg);
  CHECK(model != NULL);

  int dims[3] = {B, S, H};
  flexflow_tensor_t x = flexflow_tensor_create(model, 3, dims, "x");
  CHECK(x != NULL);

  /* pre-norm transformer block */
  int norm_axes[1] = {2};
  flexflow_tensor_t t =
      flexflow_model_add_layer_norm(model, x, 1, norm_axes, 1, 1e-5f);
  flexflow_tensor_t attn = flexflow_model_add_multihead_attention_ex(
      model, t, t, t, H, /*heads*/ 4, 0, 0, 0.0f, /*bias*/ 1, /*causal*/ 1);
  CHECK(attn != NULL);
  t = flexflow_model_add_add(model, x, attn); /* residual */
  flexflow_tensor_t h =
      flexflow_model_add_dense(model, t, 4 * H, /*gelu*/ 4, 1);
  h = flexflow_model_add_dense(model, h, H, 0, 1);
  t = flexflow_model_add_add(model, t, h);
  /* pool over sequence -> classify */
  int mean_dims[1] = {1};
  t = flexflow_model_add_mean(model, t, 1, mean_dims, 0);
  flexflow_tensor_t logits =
      flexflow_model_add_dense(model, t, CLASSES, 0, 1);
  CHECK(logits != NULL);

  flexflow_sgd_optimizer_t sgd =
      flexflow_sgd_optimizer_create(model, 0.01, 0.0, 0, 0.0);
  CHECK(sgd != NULL);
  CHECK(flexflow_model_set_sgd_optimizer(model, sgd) == 0);
  CHECK(flexflow_model_compile(model, "sparse_categorical_crossentropy",
                               "accuracy", 0.01) == 0);
  CHECK(flexflow_model_init_layers(model) == 0);

  /* dataset + dataloaders */
  float *X = (float *)malloc((size_t)NUM * S * H * sizeof(float));
  int *Y = (int *)malloc((size_t)NUM * sizeof(int));
  for (int i = 0; i < NUM * S * H; ++i)
    X[i] = (float)((i * 2654435761u) % 997) / 997.0f - 0.5f;
  for (int i = 0; i < NUM; ++i) Y[i] = i % CLASSES;
  int64_t xs[3] = {NUM, S, H};
  int64_t ys[1] = {NUM};
  flexflow_single_dataloader_t dx =
      flexflow_single_dataloader_create(model, x, X, xs, 3, 0);
  flexflow_single_dataloader_t dy =
      flexflow_single_dataloader_create_label(model, Y, ys, 1, 1);
  CHECK(dx != NULL && dy != NULL);
  CHECK(flexflow_single_dataloader_get_num_samples(dx) == NUM);

  /* the reference's training loop, verb for verb */
  double first_loss = NAN, last_loss = NAN;
  int iters = NUM / B;
  for (int epoch = 0; epoch < 2; ++epoch) {
    if (epoch == 1) /* reference LR-decay pattern: set_lr mid-training */
      flexflow_sgd_optimizer_set_lr(sgd, 0.001);
    flexflow_single_dataloader_reset(dx);
    flexflow_single_dataloader_reset(dy);
    for (int it = 0; it < iters; ++it) {
      flexflow_begin_trace(model, 111);
      CHECK(flexflow_single_dataloader_next_batch(dx) == 0);
      CHECK(flexflow_single_dataloader_next_batch(dy) == 0);
      CHECK(flexflow_model_forward(model) == 0);
      CHECK(flexflow_model_zero_gradients(model) == 0);
      CHECK(flexflow_model_backward(model) == 0);
      CHECK(flexflow_model_update(model) == 0);
      flexflow_end_trace(model, 111);
      double loss = flexflow_model_get_last_loss(model);
      CHECK(!isnan(loss));
      if (isnan(first_loss)) first_loss = loss;
      last_loss = loss;
    }
  }
  CHECK(last_loss < first_loss + 1.0); /* sane, typically decreasing */

  /* metrics verbs on the final staged batch */
  CHECK(flexflow_model_reset_metrics(model) == 0);
  CHECK(flexflow_model_compute_metrics(model) == 0);
  flexflow_perf_metrics_t pm = flexflow_model_get_perf_metrics(model);
  CHECK(pm != NULL);
  double acc = flexflow_per_metrics_get_accuracy(pm);
  CHECK(acc >= 0.0 && acc <= 100.0);
  flexflow_per_metrics_destroy(pm);

  printf("capi_attention ok (loss %.4f -> %.4f, acc %.1f%%)\n", first_loss,
         last_loss, acc);

  free(X);
  free(Y);
  flexflow_sgd_optimizer_destroy(sgd);
  flexflow_single_dataloader_destroy(dx);
  flexflow_single_dataloader_destroy(dy);
  flexflow_model_destroy(model);
  flexflow_config_destroy(cfg);
  flexflow_finalize();
  return 0;
}
