"""split_test_2: a strided conv stack driven through the graph search
(reference: examples/cpp/split_test_2/split_test_2.cc — builds the conv
tower, compiles, then runs GraphSearchHelper::graph_optimize with a
budget of 10; here the same budget flows through --budget into compile).

    python examples/split_test_2.py -b 16 --budget 10
"""

import numpy as np

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from examples.common import run_training

from flexflow_tpu import (  # noqa: E402
    FFConfig,
    FFModel,
    LossType,
    MetricsType,
    SGDOptimizer,
)


def main():
    cfg = FFConfig.parse_args()
    if not cfg.search_budget:
        cfg.search_budget = 10  # split_test_2.cc:59 graph_optimize(10, ...)
    ff = FFModel(cfg)
    # reference input: {batch, 4, 32, 32} NCHW (split_test_2.cc:27);
    # NHWC is the TPU-native layout
    x = ff.create_tensor([cfg.batch_size, 32, 32, 4], name="x")
    t = x
    # the reference loops channels[] = {4, 8, 16} but passes channels[1]
    # each time: three stride-2 valid convs of 8 output channels
    for _ in range(3):
        t = ff.conv2d(t, 8, 3, 3, 2, 2, 0, 0)
    t = ff.flat(t)
    t = ff.relu(t)
    ff.softmax(t)
    ff.compile(
        optimizer=SGDOptimizer(lr=0.001),
        loss_type=LossType.SPARSE_CATEGORICAL_CROSSENTROPY,
        metrics=[
            MetricsType.ACCURACY,
            MetricsType.SPARSE_CATEGORICAL_CROSSENTROPY,
        ],
    )
    n = cfg.batch_size * (cfg.iterations or 8)
    rng = np.random.RandomState(0)
    X = rng.randn(n, 32, 32, 4).astype(np.float32)
    y = rng.randint(0, 10, size=n).astype(np.int32)
    run_training(ff, {"x": X}, y, cfg)


if __name__ == "__main__":
    main()
