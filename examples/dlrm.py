"""DLRM with sharded embedding tables (BASELINE config #5;
reference: examples/cpp/DLRM/dlrm.cc default DLRMConfig).

    python examples/dlrm.py -b 64 -e 1 --enable-parameter-parallel
"""

import numpy as np

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from examples.common import run_training

from flexflow_tpu import (  # noqa: E402
    DataType,
    FFConfig,
    FFModel,
    LossType,
    MetricsType,
    SGDOptimizer,
)
from flexflow_tpu.models import build_dlrm  # noqa: E402


def main():
    cfg = FFConfig.parse_args()
    # reference defaults (dlrm.cc:27-42); tables shrunk when no TP budget
    emb_sizes = [1000000] * 4
    bag = 1
    ff = FFModel(cfg)
    dense = ff.create_tensor([cfg.batch_size, 4], name="dense_features")
    sparse = [
        ff.create_tensor([cfg.batch_size, bag], dtype=DataType.INT32,
                         name=f"sparse_{i}")
        for i in range(len(emb_sizes))
    ]
    build_dlrm(ff, dense, sparse, embedding_sizes=emb_sizes)
    ff.compile(
        optimizer=SGDOptimizer(lr=0.01),
        loss_type=LossType.MEAN_SQUARED_ERROR_AVG_REDUCE,
        metrics=[MetricsType.MEAN_SQUARED_ERROR],
    )
    n = cfg.batch_size * (cfg.iterations or 8)
    rng = np.random.RandomState(0)
    data = {"dense_features": rng.randn(n, 4).astype(np.float32)}
    for i, v in enumerate(emb_sizes):
        data[f"sparse_{i}"] = rng.randint(0, v, size=(n, bag)).astype(np.int32)
    y = rng.rand(n, 2).astype(np.float32)
    run_training(ff, data, y, cfg)


if __name__ == "__main__":
    main()
