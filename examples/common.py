"""Shared example-runner glue: synthetic data + the reference's
train-and-print-THROUGHPUT loop (reference: every examples/cpp/* prints
`THROUGHPUT = %.2f samples/s`, e.g. alexnet.cc:135)."""

from __future__ import annotations

import numpy as np


def run_training(model, data: dict, labels, cfg, epochs=None):
    """fit() with the config's epochs; fit prints THROUGHPUT per epoch."""
    inputs = {k: v for k, v in data.items()}
    return model.fit(
        inputs,
        labels,
        epochs=epochs or cfg.epochs,
        batch_size=cfg.batch_size,
        verbose=True,
    )


def synthetic_images(num, h, w, c=3, num_classes=10, seed=0):
    rng = np.random.RandomState(seed)
    x = rng.randn(num, h, w, c).astype(np.float32)
    y = rng.randint(0, num_classes, size=num).astype(np.int32)
    return x, y
