"""Inception-v3 on synthetic ImageNet-sized data
(reference: examples/cpp/InceptionV3/inception.cc; OSDI22 AE inception.sh).

    python examples/inception.py -b 32 -e 1 [--budget N]
"""

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from examples.common import run_training, synthetic_images

from flexflow_tpu import (  # noqa: E402
    FFConfig,
    FFModel,
    LossType,
    MetricsType,
    SGDOptimizer,
)
from flexflow_tpu.models import build_inception_v3  # noqa: E402


def main():
    cfg = FFConfig.parse_args()
    ff = FFModel(cfg)
    # reference input 299x299x3 (inception.cc top_level_task), NHWC here
    x = ff.create_tensor([cfg.batch_size, 299, 299, 3], name="image")
    build_inception_v3(ff, x, num_classes=10)
    ff.compile(
        optimizer=SGDOptimizer(lr=0.001),
        loss_type=LossType.SPARSE_CATEGORICAL_CROSSENTROPY,
        metrics=[MetricsType.ACCURACY],
    )
    n = cfg.batch_size * (cfg.iterations or 4)
    X, y = synthetic_images(n, 299, 299)
    run_training(ff, {"image": X}, y, cfg)


if __name__ == "__main__":
    main()
