"""PyTorch-frontend workload: torch.fx-trace a torch module, replay it as
an FFModel, copy the torch weights, and train (reference:
examples/python/pytorch/* — 14 scripts driving flexflow.torch's
torch_to_flexflow + PyTorchModel.apply pipeline).

    python examples/torch_mlp_import.py -b 32 -i 4 -e 1
"""

from __future__ import annotations

import os
import sys

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from examples.common import run_training  # noqa: E402

from flexflow_tpu import (  # noqa: E402
    FFConfig,
    FFModel,
    LossType,
    MetricsType,
    SGDOptimizer,
)
from flexflow_tpu.frontends.torch_fx import PyTorchModel  # noqa: E402


def build_torch_module():
    import torch

    return torch.nn.Sequential(
        torch.nn.Linear(64, 128),
        torch.nn.ReLU(),
        torch.nn.Linear(128, 128),
        torch.nn.ReLU(),
        torch.nn.Linear(128, 8),
    )


def main():
    cfg = FFConfig.parse_args()
    module = build_torch_module()
    pt = PyTorchModel(module)

    ff = FFModel(cfg)
    x = ff.create_tensor([cfg.batch_size, 64], name="input")
    logits = pt.apply(ff, [x])
    if isinstance(logits, (list, tuple)):
        logits = logits[0]
    ff.compile(
        optimizer=SGDOptimizer(lr=cfg.learning_rate),
        loss_type=LossType.SPARSE_CATEGORICAL_CROSSENTROPY,
        metrics=[MetricsType.ACCURACY],
        logits=logits,
    )
    pt.copy_weights(ff, module)  # start from the torch initialization

    n = cfg.batch_size * (cfg.iterations or 4)
    rng = np.random.RandomState(cfg.seed)
    X = rng.randn(n, 64).astype(np.float32)
    y = rng.randint(0, 8, size=n).astype(np.int32)
    run_training(ff, {"input": X}, y, cfg)


if __name__ == "__main__":
    main()
