"""CANDLE-Uno drug-response regression
(reference: examples/cpp/candle_uno/candle_uno.cc; OSDI22 AE candle_uno.sh).

    python examples/candle_uno.py -b 64 -e 1 [--budget N]
"""

import numpy as np

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from examples.common import run_training

from flexflow_tpu import (  # noqa: E402
    FFConfig,
    FFModel,
    LossType,
    MetricsType,
    SGDOptimizer,
)
from flexflow_tpu.models import build_candle_uno  # noqa: E402


def main():
    cfg = FFConfig.parse_args()
    # reference feature shapes (candle_uno.cc input_shapes)
    feature_dims = (942, 5270, 2048)
    ff = FFModel(cfg)
    feats = [
        ff.create_tensor([cfg.batch_size, d], name=f"feature_{i}")
        for i, d in enumerate(feature_dims)
    ]
    build_candle_uno(ff, feats, feature_dims=feature_dims)
    ff.compile(
        optimizer=SGDOptimizer(lr=0.001),
        loss_type=LossType.MEAN_SQUARED_ERROR_AVG_REDUCE,
        metrics=[MetricsType.MEAN_SQUARED_ERROR],
    )
    n = cfg.batch_size * (cfg.iterations or 4)
    rng = np.random.RandomState(0)
    data = {
        f"feature_{i}": rng.randn(n, d).astype(np.float32)
        for i, d in enumerate(feature_dims)
    }
    y = rng.rand(n, 1).astype(np.float32)
    run_training(ff, data, y, cfg)


if __name__ == "__main__":
    main()
