"""XDL click-through model: embedding-dominated wide model
(reference: examples/cpp/XDL/xdl.cc; OSDI22 AE xdl.sh).

    python examples/xdl.py -b 64 -e 1 --enable-parameter-parallel
"""

import numpy as np

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from examples.common import run_training

from flexflow_tpu import (  # noqa: E402
    DataType,
    FFConfig,
    FFModel,
    LossType,
    MetricsType,
    SGDOptimizer,
)
from flexflow_tpu.models import build_xdl  # noqa: E402


def main():
    cfg = FFConfig.parse_args()
    num_tables = 8  # reference xdl.cc embeddings count
    vocab = 100000
    ff = FFModel(cfg)
    sparse = [
        ff.create_tensor([cfg.batch_size, 1], dtype=DataType.INT32,
                         name=f"sparse_{i}")
        for i in range(num_tables)
    ]
    build_xdl(ff, sparse, embedding_size=vocab,
              mlp_dims=(1024, 512, 2))
    ff.compile(
        optimizer=SGDOptimizer(lr=0.01),
        loss_type=LossType.MEAN_SQUARED_ERROR_AVG_REDUCE,
        metrics=[MetricsType.MEAN_SQUARED_ERROR],
    )
    n = cfg.batch_size * (cfg.iterations or 8)
    rng = np.random.RandomState(0)
    data = {
        f"sparse_{i}": rng.randint(0, vocab, size=(n, 1)).astype(np.int32)
        for i in range(num_tables)
    }
    y = rng.rand(n, 2).astype(np.float32)
    run_training(ff, data, y, cfg)


if __name__ == "__main__":
    main()
