"""Mixture-of-Experts MNIST classifier (reference:
examples/cpp/mixture_of_experts/moe.cc — 5 experts, top-2, MNIST dims).

    python examples/moe.py -b 64 -e 1
"""

import numpy as np

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from examples.common import run_training

from flexflow_tpu import (  # noqa: E402
    FFConfig,
    FFModel,
    LossType,
    MetricsType,
    SGDOptimizer,
)
from flexflow_tpu.models import build_moe_mlp  # noqa: E402


def main():
    cfg = FFConfig.parse_args()
    ff = FFModel(cfg)
    x = ff.create_tensor([cfg.batch_size, 784], name="pixels")
    build_moe_mlp(ff, x)
    ff.compile(
        optimizer=SGDOptimizer(lr=0.001),
        loss_type=LossType.SPARSE_CATEGORICAL_CROSSENTROPY,
        metrics=[MetricsType.ACCURACY, MetricsType.SPARSE_CATEGORICAL_CROSSENTROPY],
    )
    n = cfg.batch_size * (cfg.iterations or 8)
    rng = np.random.RandomState(0)
    X = rng.randn(n, 784).astype(np.float32)
    y = rng.randint(0, 10, size=n).astype(np.int32)
    run_training(ff, {"pixels": X}, y, cfg)


if __name__ == "__main__":
    main()
