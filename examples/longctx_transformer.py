"""Long-context Transformer training (seq 8192 on ONE chip).

The reference cannot run this workload at all: its attention is a
monolithic cuDNN call per shard that materializes the [s, s] scores
(attention.cu:35) — at seq 8192 the f32 score tensor alone is 4.3 GB per
layer and the dense path measurably collapses (BENCH_LONGCTX.json: 0.6
TF/s). Here `use_flash="auto"` switches to the fused streaming kernel
past the 2 GiB score threshold, so the same builder program trains at
seq 8192+ unchanged; across chips the sequence dim shards with ring
attention (sequence_parallel_strategy).

    python examples/longctx_transformer.py [-b 1] [-i 4] [--seq 8192]
"""

from __future__ import annotations

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np  # noqa: E402

from examples.common import run_training  # noqa: E402
from flexflow_tpu import (  # noqa: E402
    ActiMode,
    FFConfig,
    FFModel,
    LossType,
    SGDOptimizer,
)


def build(cfg: FFConfig, seq: int, hidden: int = 512, heads: int = 8,
          layers: int = 2):
    ff = FFModel(cfg)
    x = ff.create_tensor([cfg.batch_size, seq, hidden], name="x")
    t = x
    for _ in range(layers):
        t = ff.multihead_attention(t, t, t, hidden, heads)
        t = ff.dense(t, hidden, activation=ActiMode.RELU, use_bias=False)
    ff.dense(t, 1, use_bias=False)
    return ff


def main():
    seq = 8192
    if "--seq" in sys.argv:
        i = sys.argv.index("--seq")
        seq = int(sys.argv[i + 1])
        del sys.argv[i : i + 2]
    explicit_batch = "-b" in sys.argv or "--batch-size" in sys.argv
    cfg = FFConfig.parse_args()
    if not explicit_batch:  # the 64 default is far too big at quadratic cost
        cfg.batch_size = 1
    cfg.allow_mixed_precision = True
    hidden = 512
    ff = build(cfg, seq, hidden=hidden)
    ff.compile(
        optimizer=SGDOptimizer(lr=0.01),
        loss_type=LossType.MEAN_SQUARED_ERROR_AVG_REDUCE,
        metrics=[],
    )
    n = cfg.batch_size * (cfg.iterations or 2)
    rng = np.random.RandomState(0)
    data = {"x": rng.randn(n, seq, hidden).astype(np.float32)}
    y = rng.randn(n, seq, 1).astype(np.float32)
    run_training(ff, data, y, cfg)


if __name__ == "__main__":
    main()
