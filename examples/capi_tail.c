/* C-API tail demo: the reference-parity entries added in round 4
 * (reference: python/flexflow_c.h:59-669) — parse_args, label tensor,
 * per-handle tensor I/O, parameter-by-id, constant_create, legion-order
 * get_dim, op_init/op_forward + interior activation reads, create2
 * dataloader, null/typed initializer entries.
 *
 * Build (after `make -C native capi`):
 *   gcc examples/capi_tail.c -Inative/include -Lnative/build -lflexflow_c \
 *       -Wl,-rpath,native/build -o /tmp/capi_tail
 *   FF_CAPI_PLATFORM=cpu /tmp/capi_tail
 */

#include <math.h>
#include <stdint.h>
#include <stdio.h>
#include <stdlib.h>

#include "flexflow_c.h"

#define CHECK(cond)                                                        \
  do {                                                                     \
    if (!(cond)) {                                                         \
      fprintf(stderr, "CHECK failed at %s:%d: %s\n", __FILE__, __LINE__,   \
              #cond);                                                      \
      return 1;                                                            \
    }                                                                      \
  } while (0)

enum { B = 8, D = 12, H = 16, C = 3, N = 32 };

int main(int argc, char **argv) {
  if (flexflow_init(argc, argv) != 0) return 1;

  char *cfg_argv[] = {(char *)"-b", (char *)"4"};
  flexflow_config_t cfg = flexflow_config_create(2, cfg_argv);
  CHECK(flexflow_config_get_batch_size(cfg) == 4);
  /* parse_args re-parses reference spellings into the SAME handle */
  char *re_argv[] = {(char *)"prog", (char *)"-b", (char *)"8",
                     (char *)"--epochs", (char *)"2"};
  flexflow_config_parse_args(cfg, re_argv, 5);
  CHECK(flexflow_config_get_batch_size(cfg) == B);
  flexflow_config_parse_args_default(cfg); /* no-op by design */

  flexflow_model_t model = flexflow_model_create(cfg);
  int dims[2] = {B, D};
  flexflow_tensor_t x = flexflow_tensor_create(model, 2, dims, "x");

  /* constant_create: a constant tensor participating in the graph */
  int cdims[2] = {B, D};
  flexflow_tensor_t cst = flexflow_constant_create(model, 2, cdims, 0.5f, 0);
  CHECK(cst != NULL);
  flexflow_tensor_t xc = flexflow_model_add_add(model, x, cst);
  CHECK(xc != NULL);

  flexflow_tensor_t h = flexflow_model_add_dense(model, xc, H, 1, 1);
  flexflow_tensor_t logits = flexflow_model_add_dense(model, h, C, 0, 1);
  CHECK(logits != NULL);

  /* null + typed initializer entries */
  flexflow_initializer_t nil = flexflow_initializer_create_null();
  (void)nil;
  flexflow_initializer_t gi = flexflow_glorot_uniform_initializer_create(7);
  flexflow_glorot_uniform_initializer_destroy(gi);
  flexflow_initializer_t zi = flexflow_zero_initializer_create();
  flexflow_zero_initializer_destroy(zi);

  CHECK(flexflow_model_compile(model, "sparse_categorical_crossentropy",
                               "accuracy", 0.05) == 0);
  CHECK(flexflow_model_init_layers(model) == 0);

  /* label tensor handle: dims come from compile() */
  flexflow_tensor_t label = flexflow_model_get_label_tensor(model);
  CHECK(label != NULL);
  CHECK(flexflow_tensor_get_num_dims(label) == 1);
  /* legion-order get_dim: axis 0 is the innermost */
  CHECK(flexflow_tensor_get_dim(x, 0) == D);
  CHECK(flexflow_tensor_get_dim(x, 1) == B);

  /* stage one batch through set_tensor (inputs + label) */
  static float xb[B * D];
  static int32_t yb[B];
  for (int i = 0; i < B * D; ++i)
    xb[i] = (float)((i * 2654435761u) % 97) / 97.0f - 0.5f;
  for (int i = 0; i < B; ++i) yb[i] = i % C;
  int xdims[2] = {B, D};
  int ydims[1] = {B};
  CHECK(flexflow_tensor_set_tensor_float(x, model, 2, xdims, xb) == 0);
  CHECK(flexflow_tensor_set_tensor_int(label, model, 1, ydims, yb) == 0);

  /* op_init / op_forward, then read the interior activation by handle */
  flexflow_op_t dense0 = flexflow_model_get_layer_by_id(model, 1);
  CHECK(dense0 != NULL);
  flexflow_op_init(dense0, model);
  flexflow_op_forward(dense0, model);
  static float hact[B * H];
  CHECK(flexflow_tensor_get_tensor_float(h, model, hact, 0) == 0);
  int nonzero = 0;
  for (int i = 0; i < B * H; ++i) {
    CHECK(!isnan(hact[i]));
    if (hact[i] != 0.0f) nonzero = 1;
  }
  CHECK(nonzero);

  /* parameter-by-id handle: weight round-trip via tensor I/O */
  flexflow_tensor_t w0 = flexflow_model_get_parameter_by_id(model, 1);
  CHECK(w0 != NULL);
  CHECK(flexflow_tensor_get_num_dims(w0) == 2);
  static float wbuf[D * H], wback[D * H];
  CHECK(flexflow_tensor_get_tensor_float(w0, model, wbuf, 0) == 0);
  for (int i = 0; i < D * H; ++i) wbuf[i] *= 0.5f;
  int wdims[2] = {D, H};
  CHECK(flexflow_tensor_set_tensor_float(w0, model, 2, wdims, wbuf) == 0);
  CHECK(flexflow_tensor_get_tensor_float(w0, model, wback, 0) == 0);
  for (int i = 0; i < D * H; ++i) CHECK(fabsf(wback[i] - wbuf[i]) < 1e-6f);

  /* parameter gradient on the staged batch */
  static float gbuf[D * H];
  CHECK(flexflow_tensor_get_tensor_float(w0, model, gbuf, 1) == 0);
  int gnonzero = 0;
  for (int i = 0; i < D * H; ++i) {
    CHECK(!isnan(gbuf[i]));
    if (gbuf[i] != 0.0f) gnonzero = 1;
  }
  CHECK(gnonzero);

  /* create2 dataloader: raw pointer + num_samples, shape from tensor */
  static float X[N * D];
  static int32_t Y[N];
  for (int i = 0; i < N * D; ++i)
    X[i] = (float)((i * 40503u) % 89) / 89.0f - 0.5f;
  for (int i = 0; i < N; ++i) Y[i] = i % C;
  flexflow_single_dataloader_t dx =
      flexflow_single_dataloader_create2(model, x, X, N, 0);
  flexflow_single_dataloader_t dy =
      flexflow_single_dataloader_create2(model, label, Y, N, 1);
  CHECK(dx != NULL && dy != NULL);
  CHECK(flexflow_single_dataloader_get_num_samples(dx) == N);

  double first = NAN, last = NAN;
  for (int it = 0; it < N / B; ++it) {
    CHECK(flexflow_single_dataloader_next_batch(dx) == 0);
    CHECK(flexflow_single_dataloader_next_batch(dy) == 0);
    CHECK(flexflow_model_forward(model) == 0);
    CHECK(flexflow_model_backward(model) == 0);
    CHECK(flexflow_model_update(model) == 0);
    double loss = flexflow_model_get_last_loss(model);
    CHECK(!isnan(loss));
    if (isnan(first)) first = loss;
    last = loss;
  }
  CHECK(last < first + 1.0);

  printf("capi_tail ok (loss %.4f -> %.4f)\n", first, last);

  flexflow_single_dataloader_destroy(dx);
  flexflow_single_dataloader_destroy(dy);
  flexflow_handle_destroy(label);
  flexflow_handle_destroy(w0);
  flexflow_model_destroy(model);
  flexflow_config_destroy(cfg);
  flexflow_finalize();
  return 0;
}
