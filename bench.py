"""Benchmark driver: flagship Transformer training throughput on TPU.

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline"}.

The workload is the reference's headline Transformer benchmark
(reference: examples/cpp/Transformer/transformer.cc — 12 layers, hidden
1024, 16 heads, seq 512, bs 8/chip, SGD, MSE; prints THROUGHPUT samples/s).
`vs_baseline` is measured against BASELINE_SAMPLES_PER_SEC, the f32
data-parallel number of this rebuild measured with the same methodology.

Timing methodology: on the tunneled TPU platform `block_until_ready` does
not synchronize with remote execution, and a device->host readback carries
a large constant RTT. So we time two chained runs of N1 and N2 steps, each
ended by a scalar readback (which forces the whole dependency chain), and
difference them: per-step = (t2 - t1) / (N2 - N1). The readback RTT and
dispatch constants cancel.
"""

from __future__ import annotations

import json
import sys
import time

# f32 single-chip data-parallel throughput of this framework measured with
# the differencing methodology below on one TPU v5e (the reference repo
# publishes no figures — BASELINE.md; its perf story is self-relative).
BASELINE_SAMPLES_PER_SEC = 234.0


def _timed_chain(step, params, opt_state, batch, key, n):
    import numpy as np

    t0 = time.perf_counter()
    p, o = params, opt_state
    loss = None
    for _ in range(n):
        p, o, loss, _ = step(p, o, batch, key)
    _ = float(np.asarray(loss))  # forces the whole chain on the tunnel
    return time.perf_counter() - t0, p, o


def main():
    import jax

    sys.path.insert(0, __file__.rsplit("/", 1)[0])
    from examples.transformer import build_transformer, synthetic_batch
    from flexflow_tpu import FFConfig

    batch_size, seq, hidden, heads, layers = 8, 512, 1024, 16, 12
    cfg = FFConfig(batch_size=batch_size, learning_rate=0.01)
    cfg.allow_mixed_precision = True  # --allow-tensor-op-math-conversion
    model, _ = build_transformer(
        cfg,
        batch_size=batch_size,
        seq_len=seq,
        hidden=hidden,
        num_heads=heads,
        num_layers=layers,
    )
    step = model.executor.train_step()
    batch = model.executor.shard_batch(synthetic_batch(batch_size, seq, hidden))
    params, opt_state = model.params, model.opt_state
    key = jax.random.PRNGKey(0)

    # compile + warmup
    _, params, opt_state = _timed_chain(step, params, opt_state, batch, key, 2)

    n1, n2 = 10, 60
    t1, params, opt_state = _timed_chain(step, params, opt_state, batch, key, n1)
    t2, params, opt_state = _timed_chain(step, params, opt_state, batch, key, n2)
    per_step = (t2 - t1) / (n2 - n1)
    thpt = batch_size / per_step

    print(
        json.dumps(
            {
                "metric": "transformer_12L_1024h_seq512_train_throughput",
                "value": round(thpt, 2),
                "unit": "samples/s",
                "vs_baseline": round(thpt / BASELINE_SAMPLES_PER_SEC, 3),
            }
        )
    )


if __name__ == "__main__":
    main()
