"""Benchmark driver: flagship Transformer training throughput on TPU.

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline"}.

The workload is the reference's headline Transformer benchmark
(reference: examples/cpp/Transformer/transformer.cc — 12 layers, hidden
1024, 16 heads, seq 512, bs 8/chip, SGD, MSE; prints THROUGHPUT samples/s).
`vs_baseline` is measured against BASELINE_SAMPLES_PER_SEC, the first
recorded single-chip data-parallel number of this rebuild (the reference
repo publishes no figures — BASELINE.md; its story is self-relative).
"""

from __future__ import annotations

import json
import sys
import time

# First recorded throughput of this framework's round-1 data-parallel
# Transformer step on one v5e-lite chip; later rounds must beat it.
BASELINE_SAMPLES_PER_SEC = 12.0


def main():
    import jax

    sys.path.insert(0, __file__.rsplit("/", 1)[0])
    from examples.transformer import build_transformer, synthetic_batch

    batch_size, seq, hidden, heads, layers = 8, 512, 1024, 16, 12
    model, _ = build_transformer(
        batch_size=batch_size,
        seq_len=seq,
        hidden=hidden,
        num_heads=heads,
        num_layers=layers,
    )
    step = model.executor.train_step()
    batch = model.executor.shard_batch(
        synthetic_batch(batch_size, seq, hidden)
    )
    params, opt_state = model.params, model.opt_state
    rng = jax.random.PRNGKey(0)

    # warmup / compile
    for _ in range(2):
        rng, k = jax.random.split(rng)
        params, opt_state, loss, _ = step(params, opt_state, batch, k)
    jax.block_until_ready(loss)

    iters = 10
    t0 = time.perf_counter()
    for _ in range(iters):
        rng, k = jax.random.split(rng)
        params, opt_state, loss, _ = step(params, opt_state, batch, k)
    jax.block_until_ready(loss)
    elapsed = time.perf_counter() - t0

    thpt = batch_size * iters / elapsed
    print(
        json.dumps(
            {
                "metric": "transformer_12L_1024h_seq512_train_throughput",
                "value": round(thpt, 2),
                "unit": "samples/s",
                "vs_baseline": round(thpt / BASELINE_SAMPLES_PER_SEC, 3),
            }
        )
    )


if __name__ == "__main__":
    main()
