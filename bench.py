"""Benchmark driver: flagship Transformer training throughput on TPU,
plus the training-observability gates.

Default mode prints ONE JSON line: {"metric", "value", "unit",
"vs_baseline"} — the reference's headline Transformer benchmark
(reference: examples/cpp/Transformer/transformer.cc — 12 layers, hidden
1024, 16 heads, seq 512, bs 8/chip, SGD, MSE; prints THROUGHPUT
samples/s). `vs_baseline` is measured against
BASELINE_SAMPLES_PER_SEC, the f32 data-parallel number of this rebuild
measured with the same methodology. Timing methodology (round 2):
on-device lax.scan chain differencing with min-over-reps —
flexflow_tpu/utils/benchmark.py has the details.

Two additional modes back the search/training observability CI job:

* ``--train-telemetry [--smoke]`` — the fit-loop overhead gate
  (BENCH_TRAIN_TELEMETRY.json): three identically-seeded models train
  interleaved with telemetry off / in-memory / full-export. The
  in-memory configuration must hold >= 0.98x the uninstrumented
  throughput (the same <=2% contract bench_serve.py --telemetry holds
  for serving), final parameters must be BIT-IDENTICAL across modes
  (observation must not perturb training), and the full-export
  artifacts must validate against the checked-in schemas. Exits
  nonzero on any violation.
* ``--audit [--smoke]`` — the predicted-vs-measured cost-model audit
  (BENCH_COST_AUDIT.json): compile the bench model, price it with the
  search's CostModel, measure the real executor step, and export
  cost_model_error_ratio per op family plus the calibration-table
  write-back. Exits nonzero when the audit produces no per-family
  ratios (the artifact is the deliverable — on CPU the analytic model
  predicts TPU times, so the RATIO is informative, not gated; on TPU
  with --measure-costs it converges toward 1).
"""

from __future__ import annotations

import json
import os
import sys

# f32 single-chip data-parallel throughput of this framework measured with
# the scan-differencing methodology below on one TPU v5e (the reference
# repo publishes no figures — BASELINE.md; its perf story is
# self-relative).
BASELINE_SAMPLES_PER_SEC = 238.0

HERE = os.path.dirname(os.path.abspath(__file__))


def run_flagship():
    from examples.transformer import build_transformer, synthetic_batch
    from flexflow_tpu import FFConfig
    from flexflow_tpu.utils.benchmark import measure_train_step

    batch_size, seq, hidden, heads, layers = 8, 512, 1024, 16, 12
    cfg = FFConfig(batch_size=batch_size, learning_rate=0.01)
    cfg.allow_mixed_precision = True  # --allow-tensor-op-math-conversion
    model, _ = build_transformer(
        cfg,
        batch_size=batch_size,
        seq_len=seq,
        hidden=hidden,
        num_heads=heads,
        num_layers=layers,
    )
    batch = model.executor.shard_batch(synthetic_batch(batch_size, seq, hidden))
    per_step = measure_train_step(model, batch, reps=8, rep_sleep_s=2.0)
    thpt = batch_size / per_step

    print(
        json.dumps(
            {
                "metric": "transformer_12L_1024h_seq512_train_throughput",
                "value": round(thpt, 2),
                "unit": "samples/s",
                "vs_baseline": round(thpt / BASELINE_SAMPLES_PER_SEC, 3),
            }
        )
    )


def _build_train_model(seed=0, batch=32, hidden=128, layers=3, classes=8):
    """Small dense stack for the CPU-fast observability gates; one
    model per telemetry mode, identical seeds → identical init."""
    from flexflow_tpu import ActiMode, FFConfig, FFModel, SGDOptimizer
    from flexflow_tpu.core.types import LossType

    cfg = FFConfig(batch_size=batch, seed=seed)
    model = FFModel(cfg)
    x = model.create_tensor([batch, hidden], name="x")
    t = x
    for i in range(layers):
        t = model.dense(t, hidden, activation=ActiMode.RELU, name=f"d{i}")
    t = model.dense(t, classes, name="head")
    model.compile(
        optimizer=SGDOptimizer(lr=0.01),
        loss_type=LossType.SPARSE_CATEGORICAL_CROSSENTROPY,
    )
    return model


def run_train_telemetry(smoke: bool = False):
    """Fit-loop telemetry gate; writes BENCH_TRAIN_TELEMETRY.json."""
    import tempfile

    import numpy as np

    from flexflow_tpu.telemetry import (
        Telemetry,
        validate_metrics_jsonl_file,
        validate_metrics_text,
        validate_trace_file,
    )

    batch, hidden, layers = 32, (96 if smoke else 192), 3
    iters = 24 if smoke else 64
    reps = 2 if smoke else 3
    n = batch * iters
    rng = np.random.default_rng(0)
    X = rng.standard_normal((n, hidden)).astype(np.float32)
    y = rng.integers(0, 8, size=(n,)).astype(np.int32)

    tmp = tempfile.mkdtemp(prefix="flexflow_train_tele_")
    paths = {
        "metrics_out": os.path.join(tmp, "train.prom"),
        "metrics_jsonl": os.path.join(tmp, "train.jsonl"),
        "trace": os.path.join(tmp, "train_trace.json"),
    }
    modes = ("off", "on", "full")
    models = {
        m: _build_train_model(seed=0, batch=batch, hidden=hidden,
                              layers=layers)
        for m in modes
    }
    def make_tele(mode):
        # a fresh bundle per rep: fit()'s iteration counter is
        # per-call, and the full mode's writers truncate on open, so
        # the LAST rep's files are the validated artifact
        if mode == "off":
            return None
        if mode == "on":  # in-memory metrics only, no tracer, no I/O
            return Telemetry()
        return Telemetry(**paths)

    for m in modes:  # warm the jit off the clock
        models[m].init_operators()

    tps = {m: [] for m in modes}
    last_tele = {}
    for rep in range(reps):  # interleaved: all modes see the same drift
        for m in modes:
            tele = make_tele(m)
            last_tele[m] = tele
            hist = models[m].fit(
                X, y, epochs=1, batch_size=batch, verbose=False,
                telemetry=tele,
            )
            tps[m].append(hist[0]["throughput"])
    mean = {m: sum(v) / len(v) for m, v in tps.items()}
    on_ratio = mean["on"] / mean["off"]
    full_ratio = mean["full"] / mean["off"]

    # observation must not perturb training: final params bit-identical
    ref = models["off"].executor.export_host_params(models["off"].params)
    mismatched = []
    for m in ("on", "full"):
        got = models[m].executor.export_host_params(models[m].params)
        same = set(ref) == set(got) and all(
            len(ref[g]) == len(got[g])
            and all(
                np.array_equal(np.asarray(a), np.asarray(b))
                for a, b in zip(ref[g], got[g])
            )
            for g in ref
        )
        if not same:
            mismatched.append(m)
    if mismatched:
        raise SystemExit(
            f"telemetry perturbed training in mode(s) {mismatched}: "
            "final params differ from the uninstrumented run"
        )

    last_tele["full"].flush()
    errs = (
        validate_trace_file(paths["trace"], errors="list")
        + validate_metrics_text(
            open(paths["metrics_out"]).read(), errors="list"
        )
        + validate_metrics_jsonl_file(paths["metrics_jsonl"], errors="list")
    )
    if errs:
        raise SystemExit(
            f"training telemetry artifacts failed validation: {errs[:5]}"
        )
    text = open(paths["metrics_out"]).read()
    missing = [
        s
        for s in (
            "train_loss", "train_step_time_s", "train_iterations_total",
            "train_examples_total", "train_jit_builds",
            "train_recompiles_total",
        )
        if s not in text
    ]
    if missing:
        raise SystemExit(f"train_* series missing from exposition: {missing}")

    doc = {
        "preset": "smoke" if smoke else "medium",
        "iterations_per_rep": iters,
        "reps": reps,
        "samples_per_s": {m: round(mean[m], 2) for m in modes},
        "on_off_ratio": round(on_ratio, 4),
        "full_off_ratio": round(full_ratio, 4),
        "params_identical": True,
        "artifacts_valid": True,
        "jsonl_rows": sum(1 for _ in open(paths["metrics_jsonl"])),
    }
    with open(os.path.join(HERE, "BENCH_TRAIN_TELEMETRY.json"), "w") as f:
        json.dump(doc, f, indent=2)
        f.write("\n")
    print(json.dumps(doc))
    if on_ratio < 0.98:
        raise SystemExit(
            f"in-memory training telemetry costs more than 2%: "
            f"on/off ratio {on_ratio:.4f} < 0.98"
        )


def run_audit(smoke: bool = False):
    """Predicted-vs-measured audit; writes BENCH_COST_AUDIT.json."""
    import tempfile

    from flexflow_tpu.telemetry import MetricsRegistry

    model = _build_train_model(
        seed=0, batch=32, hidden=96 if smoke else 256,
        layers=2 if smoke else 4,
    )
    calib = os.path.join(
        tempfile.mkdtemp(prefix="flexflow_audit_"), "calibration.json"
    )
    reg = MetricsRegistry()
    res = model.audit_cost_model(
        registry=reg,
        reps=2 if smoke else 4,
        profile_iters=2 if smoke else 5,
        calibration_file=calib,
    )
    print(res.describe())
    ratios = {
        f.family: f.error_ratio
        for f in res.families.values()
        if f.measured_s > 0
    }
    if not ratios:
        raise SystemExit("audit produced no per-family error ratios")
    if reg.get("cost_model_error_ratio", labels={"family": "_step"}) is None:
        raise SystemExit("cost_model_error_ratio{family=_step} not exported")
    with open(calib) as f:
        caldoc = json.load(f)
    if "audit" not in caldoc:
        raise SystemExit("audit write-back missing from calibration table")
    doc = {
        "preset": "smoke" if smoke else "medium",
        **res.to_doc(),
        "calibration_written": True,
    }
    with open(os.path.join(HERE, "BENCH_COST_AUDIT.json"), "w") as f:
        json.dump(doc, f, indent=2)
        f.write("\n")
    print(json.dumps({"metric": "cost_model_step_error_ratio",
                      "value": round(res.step_error_ratio, 6),
                      "unit": "predicted/measured"}))


def main():
    sys.path.insert(0, HERE)
    args = sys.argv[1:]
    smoke = "--smoke" in args
    if "--train-telemetry" in args:
        run_train_telemetry(smoke=smoke)
    elif "--audit" in args:
        run_audit(smoke=smoke)
    else:
        run_flagship()


if __name__ == "__main__":
    main()
