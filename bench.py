"""Benchmark driver: flagship Transformer training throughput on TPU.

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline"}.

The workload is the reference's headline Transformer benchmark
(reference: examples/cpp/Transformer/transformer.cc — 12 layers, hidden
1024, 16 heads, seq 512, bs 8/chip, SGD, MSE; prints THROUGHPUT samples/s).
`vs_baseline` is measured against BASELINE_SAMPLES_PER_SEC, the f32
data-parallel number of this rebuild measured with the same methodology.

Timing methodology (round 2): on-device lax.scan chain differencing
with min-over-reps — flexflow_tpu/utils/benchmark.py has the details.
"""

from __future__ import annotations

import json
import sys

# f32 single-chip data-parallel throughput of this framework measured with
# the scan-differencing methodology below on one TPU v5e (the reference
# repo publishes no figures — BASELINE.md; its perf story is
# self-relative).
BASELINE_SAMPLES_PER_SEC = 238.0


def main():
    sys.path.insert(0, __file__.rsplit("/", 1)[0])
    from examples.transformer import build_transformer, synthetic_batch
    from flexflow_tpu import FFConfig
    from flexflow_tpu.utils.benchmark import measure_train_step

    batch_size, seq, hidden, heads, layers = 8, 512, 1024, 16, 12
    cfg = FFConfig(batch_size=batch_size, learning_rate=0.01)
    cfg.allow_mixed_precision = True  # --allow-tensor-op-math-conversion
    model, _ = build_transformer(
        cfg,
        batch_size=batch_size,
        seq_len=seq,
        hidden=hidden,
        num_heads=heads,
        num_layers=layers,
    )
    batch = model.executor.shard_batch(synthetic_batch(batch_size, seq, hidden))
    per_step = measure_train_step(model, batch, reps=8, rep_sleep_s=2.0)
    thpt = batch_size / per_step

    print(
        json.dumps(
            {
                "metric": "transformer_12L_1024h_seq512_train_throughput",
                "value": round(thpt, 2),
                "unit": "samples/s",
                "vs_baseline": round(thpt / BASELINE_SAMPLES_PER_SEC, 3),
            }
        )
    )


if __name__ == "__main__":
    main()
